"""Shuffle graph builder + task bodies (reference shuffle/_shuffle.py,
_rechunk.py graph shapes).

``p2p_shuffle`` repartitions a list of record-partition futures into
``npartitions_out`` hash partitions; ``p2p_rechunk`` re-tiles a 1-D
chunked array.  Both build the O(N+M) transfer/barrier/unpack graph whose
data plane is the direct worker->worker push engine in ``shuffle.core``.
"""

from __future__ import annotations

import uuid
from typing import Any, Callable

from distributed_tpu.graph.spec import Graph, TaskRef, TaskSpec
from distributed_tpu.shuffle.core import (
    ShuffleSpec,
    concat_records,
    make_keyed_splitter,
    split_records_by_hash,
)


# ------------------------------------------------------------ task bodies
# (async: they run on the worker event loop and reach the engine through
# the execution context, reference shuffle/_shuffle.py shuffle_transfer)

async def shuffle_transfer(data: Any, spec_msg: dict, partition_id: int,
                           key: Callable | None = None) -> int:
    from distributed_tpu.worker.context import get_worker

    worker = get_worker()
    run = worker.shuffle.get_or_create(ShuffleSpec.from_msg(spec_msg))
    splitter = make_keyed_splitter(key) if key is not None else split_records_by_hash
    await run.add_partition(data, partition_id, splitter)
    return partition_id


async def shuffle_barrier(spec_msg: dict, *transfer_results: int) -> int:
    from distributed_tpu.worker.context import get_worker

    worker = get_worker()
    run = worker.shuffle.get_or_create(ShuffleSpec.from_msg(spec_msg))
    await run.barrier()
    return run.run_id


async def shuffle_unpack(spec_msg: dict, partition_id: int,
                         barrier_result: int) -> Any:
    from distributed_tpu.worker.context import get_worker

    worker = get_worker()
    run = worker.shuffle.get_or_create(ShuffleSpec.from_msg(spec_msg))
    return await run.get_output_partition(partition_id, concat_records)


# ------------------------------------------------------- rechunk variants

async def rechunk_transfer(chunk: Any, spec_msg: dict, partition_id: int,
                           old_offset: int, new_bounds: tuple) -> int:
    """Route slices of a 1-D chunk to their output-chunk owners
    (reference shuffle/_rechunk.py rechunk_transfer)."""
    from distributed_tpu.worker.context import get_worker

    worker = get_worker()
    run = worker.shuffle.get_or_create(ShuffleSpec.from_msg(spec_msg))

    def splitter(data: Any, npartitions: int) -> dict[int, Any]:
        out: dict[int, Any] = {}
        n = len(data)
        for j in range(npartitions):
            lo, hi = new_bounds[j], new_bounds[j + 1]
            s = max(lo - old_offset, 0)
            e = min(hi - old_offset, n)
            if s < e:
                # tag with the absolute offset so assembly can sort
                out[j] = (old_offset + s, data[s:e])
        return out

    await run.add_partition(chunk, partition_id, splitter)
    return partition_id


async def rechunk_unpack(spec_msg: dict, partition_id: int,
                         barrier_result: int) -> Any:
    from distributed_tpu.worker.context import get_worker

    worker = get_worker()
    run = worker.shuffle.get_or_create(ShuffleSpec.from_msg(spec_msg))

    def assembler(shards: list) -> Any:
        import numpy as np

        pieces = sorted(shards, key=lambda t: t[0])
        arrays = [p[1] for p in pieces]
        if not arrays:
            return np.empty(0)
        if isinstance(arrays[0], np.ndarray):
            return np.concatenate(arrays)
        out: list = []
        for a in arrays:
            out.extend(a)
        return out

    return await run.get_output_partition(partition_id, assembler)


# --------------------------------------------------------- graph builders

async def _worker_for(client: Any, npartitions_out: int) -> dict[int, str]:
    info = await client.scheduler_info()
    addrs = sorted(info["workers"])
    if not addrs:
        raise RuntimeError("no workers available for shuffle")
    return {j: addrs[j % len(addrs)] for j in range(npartitions_out)}


async def p2p_shuffle(
    client: Any,
    inputs: list,
    npartitions_out: int | None = None,
    key: Callable | None = None,
) -> list:
    """Hash-shuffle record partitions (futures) into npartitions_out
    partitions; returns output futures."""
    npartitions_out = npartitions_out or len(inputs)
    shuffle_id = f"shuffle-{uuid.uuid4().hex[:12]}"
    worker_for = await _worker_for(client, npartitions_out)
    spec = ShuffleSpec(shuffle_id, 1, npartitions_out, worker_for)
    msg = spec.to_msg()

    g = Graph()
    transfer_keys = []
    for i, fut in enumerate(inputs):
        k = f"{shuffle_id}-transfer-{i}"
        g.tasks[k] = TaskSpec(
            shuffle_transfer, (TaskRef(fut.key), msg, i, key)
        )
        transfer_keys.append(k)
    barrier_key = f"{shuffle_id}-barrier"
    g.tasks[barrier_key] = TaskSpec(
        shuffle_barrier, (msg, *[TaskRef(k) for k in transfer_keys]),
    )
    unpack_keys = []
    annotations = {}
    for j in range(npartitions_out):
        k = f"{shuffle_id}-unpack-{j}"
        g.tasks[k] = TaskSpec(shuffle_unpack, (msg, j, TaskRef(barrier_key)))
        unpack_keys.append(k)
        annotations[k] = {"workers": [worker_for[j]]}

    # inputs must exist as graph nodes for dependency wiring
    futs = client._graph_to_futures(
        dict(g.tasks), unpack_keys, annotations_by_key=annotations,
    )
    return [futs[k] for k in unpack_keys]


async def p2p_rechunk(client: Any, chunks: list, chunk_sizes: list[int],
                      new_chunk_sizes: list[int]) -> list:
    """Re-tile a 1-D chunked array (futures of chunks) onto new chunk
    boundaries (reference shuffle/_rechunk.py)."""
    assert sum(chunk_sizes) == sum(new_chunk_sizes)
    npartitions_out = len(new_chunk_sizes)
    shuffle_id = f"rechunk-{uuid.uuid4().hex[:12]}"
    worker_for = await _worker_for(client, npartitions_out)
    spec = ShuffleSpec(shuffle_id, 1, npartitions_out, worker_for)
    msg = spec.to_msg()

    old_offsets = [0]
    for s in chunk_sizes:
        old_offsets.append(old_offsets[-1] + s)
    new_bounds = [0]
    for s in new_chunk_sizes:
        new_bounds.append(new_bounds[-1] + s)
    new_bounds_t = tuple(new_bounds)

    g = Graph()
    transfer_keys = []
    for i, fut in enumerate(chunks):
        k = f"{shuffle_id}-transfer-{i}"
        g.tasks[k] = TaskSpec(
            rechunk_transfer,
            (TaskRef(fut.key), msg, i, old_offsets[i], new_bounds_t),
        )
        transfer_keys.append(k)
    barrier_key = f"{shuffle_id}-barrier"
    g.tasks[barrier_key] = TaskSpec(
        shuffle_barrier, (msg, *[TaskRef(k) for k in transfer_keys]),
    )
    unpack_keys = []
    annotations = {}
    for j in range(npartitions_out):
        k = f"{shuffle_id}-unpack-{j}"
        g.tasks[k] = TaskSpec(rechunk_unpack, (msg, j, TaskRef(barrier_key)))
        unpack_keys.append(k)
        annotations[k] = {"workers": [worker_for[j]]}

    futs = client._graph_to_futures(
        dict(g.tasks), unpack_keys, annotations_by_key=annotations,
    )
    return [futs[k] for k in unpack_keys]
