"""P2P shuffle engine (reference shuffle/_core.py, _worker_plugin.py).

All-to-all repartitioning that bypasses the task-graph data model:
N input partitions -> shards pushed directly worker->worker -> M output
partitions, at O(N+M) scheduler tasks instead of O(N*M)
(reference shuffle/_core.py:62-380).

Graph shape (built by ``distributed_tpu.shuffle.api``):

    transfer(i):  split input partition i by output -> push shards to the
                  owner of each output partition (direct RPC)
    barrier:      after all transfers -> broadcast inputs_done to every
                  participant
    unpack(j):    restricted to worker_for[j] -> await inputs_done,
                  assemble output partition j from received shards

Runs are fenced by ``run_id`` epochs like the reference
(shuffle/_worker_plugin.py:36): stale shards from a previous attempt of
the same shuffle id are rejected, enabling restart after worker loss.
"""

from __future__ import annotations

import asyncio
import logging
from collections import defaultdict
from typing import Any, Callable

from distributed_tpu.exceptions import CommClosedError
from distributed_tpu.protocol.serialize import Serialize, unwrap

logger = logging.getLogger("distributed_tpu.shuffle")


class ShuffleClosedError(RuntimeError):
    pass


class ShuffleSpec:
    """Declarative description of one shuffle (reference shuffle/_core.py:421)."""

    __slots__ = ("id", "run_id", "npartitions_out", "worker_for")

    def __init__(self, id: str, run_id: int, npartitions_out: int,
                 worker_for: dict[int, str]):
        self.id = id
        self.run_id = run_id
        self.npartitions_out = npartitions_out
        self.worker_for = dict(worker_for)

    @property
    def participants(self) -> list[str]:
        return sorted(set(self.worker_for.values()))

    def to_msg(self) -> dict:
        return {
            "id": self.id,
            "run_id": self.run_id,
            "npartitions_out": self.npartitions_out,
            "worker_for": {str(k): v for k, v in self.worker_for.items()},
        }

    @classmethod
    def from_msg(cls, msg: dict) -> "ShuffleSpec":
        return cls(
            msg["id"], msg["run_id"], msg["npartitions_out"],
            {int(k): v for k, v in msg["worker_for"].items()},
        )


class ShuffleRun:
    """Per-worker engine for one (id, run_id) (reference shuffle/_core.py:62)."""

    def __init__(self, spec: ShuffleSpec, worker: Any):
        self.spec = spec
        self.worker = worker
        # output partition -> {source tag: shard}; keyed by source so a
        # recomputed transfer re-pushing its shards is idempotent
        self.shards: defaultdict[int, dict[int, Any]] = defaultdict(dict)
        self.inputs_done = asyncio.Event()
        self.closed = False
        self.bytes_received = 0
        self.transfers_done: set[int] = set()
        self.outputs_served: set[int] = set()
        self.local_outputs_left = sum(
            1 for addr in spec.worker_for.values() if addr == worker.address
        )
        from distributed_tpu.utils.misc import time as _now

        self.last_activity = _now()

    def touch(self) -> None:
        from distributed_tpu.utils.misc import time as _now

        self.last_activity = _now()

    @property
    def id(self) -> str:
        return self.spec.id

    @property
    def run_id(self) -> int:
        return self.spec.run_id

    # ---------------------------------------------------------- data plane

    async def add_partition(self, data: Any, partition_id: int,
                            splitter: Callable) -> int:
        """Split one input partition and push shards to their owners
        (reference shuffle/_core.py:331)."""
        if self.closed:
            raise ShuffleClosedError(self.id)
        self.touch()
        out_shards = splitter(data, self.spec.npartitions_out)
        by_worker: defaultdict[str, dict[int, list]] = defaultdict(dict)
        for j, shard in out_shards.items():
            addr = self.spec.worker_for[j % self.spec.npartitions_out]
            by_worker[addr].setdefault(j, []).append((partition_id, shard))

        async def send(addr: str, shards: dict):
            if addr == self.worker.address:
                self.receive(shards)
                return
            # the spec rides along: the receiver may not have seen this
            # shuffle yet (it owns outputs but runs no transfer tasks)
            resp = await self.worker.rpc(addr).shuffle_receive(
                id=self.id, run_id=self.run_id,
                spec=self.spec.to_msg(),
                shards=Serialize(shards),
            )
            if resp.get("status") != "OK":
                raise RuntimeError(
                    f"shuffle_receive failed on {addr}: {resp!r}"
                )

        await asyncio.gather(*(send(a, s) for a, s in by_worker.items()))
        self.transfers_done.add(partition_id)
        return partition_id

    def receive(self, shards: dict) -> None:
        """Accept shards pushed by a peer (reference shuffle/_core.py:260)."""
        if self.closed:
            raise ShuffleClosedError(self.id)
        self.touch()
        for j, tagged in shards.items():
            bucket = self.shards[int(j)]
            for tag, shard in tagged:
                bucket[tag] = shard

    async def barrier(self) -> None:
        """All inputs transferred: notify every participant
        (reference shuffle/_core.py:190)."""
        async def notify(addr: str):
            if addr == self.worker.address:
                self.inputs_done.set()
                return
            try:
                await self.worker.rpc(addr).shuffle_inputs_done(
                    id=self.id, run_id=self.run_id, spec=self.spec.to_msg()
                )
            except (CommClosedError, OSError) as e:
                raise RuntimeError(
                    f"barrier could not reach {addr}"
                ) from e

        await asyncio.gather(*(notify(a) for a in self.spec.participants))

    async def get_output_partition(self, j: int, assembler: Callable,
                                   timeout: float = 30.0) -> Any:
        """Assemble output partition j (reference shuffle/_core.py:353)."""
        self.touch()
        await asyncio.wait_for(self.inputs_done.wait(), timeout)
        self.touch()
        if j in self.outputs_served:
            # the bucket was consumed by a previous serve: a recomputed
            # unpack must not silently get an empty partition — fail the
            # run so the scheduler restarts it under a new run_id epoch
            # (reference fails stale/duplicate fetches the same way)
            raise ShuffleClosedError(
                f"{self.id}: output partition {j} already served; "
                f"restart required"
            )
        self.outputs_served.add(j)
        bucket = self.shards.pop(j, {})
        self.local_outputs_left -= 1
        if self.local_outputs_left <= 0:
            # every local output served: schedule forgetting this run so
            # long-lived workers don't accumulate one run per shuffle id
            # (delayed: a rescheduled unpack may still re-request briefly)
            self.worker.shuffle.schedule_cleanup(self.id, self.run_id)
        return assembler([bucket[tag] for tag in sorted(bucket)])

    def close(self) -> None:
        self.closed = True
        self.shards.clear()


class ShuffleWorkerExtension:
    """Caches active runs by (id, run_id); fences stale epochs
    (reference shuffle/_worker_plugin.py:36)."""

    def __init__(self, worker: Any):
        self.worker = worker
        self.runs: dict[str, ShuffleRun] = {}  # id -> newest run
        worker.handlers["shuffle_receive"] = self.shuffle_receive
        worker.handlers["shuffle_inputs_done"] = self.shuffle_inputs_done

    def get_or_create(self, spec: ShuffleSpec) -> ShuffleRun:
        run = self.runs.get(spec.id)
        if run is not None:
            if run.run_id > spec.run_id:
                raise ShuffleClosedError(
                    f"{spec.id} run {spec.run_id} superseded by {run.run_id}"
                )
            if run.run_id == spec.run_id:
                run.touch()
                return run
            run.close()  # stale epoch: replace
        run = self.runs[spec.id] = ShuffleRun(spec, self.worker)
        # TTL backstop: runs whose outputs are never unpacked (transfer-only
        # workers, cancelled shuffles) must not accumulate forever
        self.schedule_cleanup(spec.id, spec.run_id, delay=self.RUN_TTL)
        return run

    def _get_checked(self, id: str, run_id: int) -> ShuffleRun | None:
        run = self.runs.get(id)
        if run is None or run.run_id != run_id:
            return None
        return run

    # ------------------------------------------------------------ handlers

    async def shuffle_receive(self, id: str = "", run_id: int = 0,
                              spec: dict | None = None,
                              shards: Any = None) -> dict:
        run = self.runs.get(id)
        if run is not None and run.run_id > run_id:
            return {"status": "stale", "id": id, "run_id": run_id}
        if run is None or run.run_id < run_id:
            # first contact for this (id, run_id): build the run from the
            # spec riding on the message
            if spec is None:
                return {"status": "unknown-run", "id": id, "run_id": run_id}
            run = self.get_or_create(ShuffleSpec.from_msg(spec))
        run.receive(unwrap(shards))
        return {"status": "OK"}

    async def shuffle_inputs_done(self, id: str = "", run_id: int = 0,
                                  spec: dict | None = None) -> dict:
        run = self._get_checked(id, run_id)
        if run is None:
            if spec is None:
                return {"status": "stale"}
            run = self.get_or_create(ShuffleSpec.from_msg(spec))
        run.inputs_done.set()
        return {"status": "OK"}

    RUN_TTL = 300.0  # forget idle runs after this long

    def schedule_cleanup(self, id: str, run_id: int, delay: float = 30.0) -> None:
        """Forget a run after a grace period; reschedules while active."""

        async def _cleanup() -> None:
            from distributed_tpu.utils.misc import time as _now

            run = self.runs.get(id)
            if run is None or run.run_id != run_id:
                return
            idle = _now() - run.last_activity
            # idleness required even with no local outputs left: a
            # transfer-only worker is still actively pushing shards
            if (run.local_outputs_left <= 0 and idle >= 5.0) or idle >= self.RUN_TTL:
                run.close()
                del self.runs[id]
            else:
                self.schedule_cleanup(
                    id, run_id, delay=max(self.RUN_TTL - idle, 5.0)
                )

        self.worker._ongoing_background_tasks.call_later(delay, _cleanup)

    def close(self) -> None:
        for run in self.runs.values():
            run.close()
        self.runs.clear()


# ------------------------------------------------------------ splitters

def stable_hash(x: Any) -> int:
    """Process-independent hash: builtin hash() is randomized per
    interpreter for str/bytes, which would route equal keys hashed on
    different workers to different partitions."""
    import hashlib

    if isinstance(x, bool):
        x = repr(x).encode()
    elif isinstance(x, int):
        return x
    if isinstance(x, str):
        x = x.encode()
    elif not isinstance(x, bytes):
        x = repr(x).encode()
    return int.from_bytes(
        hashlib.blake2b(x, digest_size=8).digest(), "big"
    )


def split_records_by_hash(data: Any, npartitions: int) -> dict[int, list]:
    """Generic record splitter: hash each record (or its key for
    (key, value) pairs is the caller's concern) into an output partition."""
    out: defaultdict[int, list] = defaultdict(list)
    for rec in data:
        out[stable_hash(rec) % npartitions].append(rec)
    return dict(out)


def make_keyed_splitter(key: Callable) -> Callable:
    def splitter(data: Any, npartitions: int) -> dict[int, list]:
        out: defaultdict[int, list] = defaultdict(list)
        for rec in data:
            out[stable_hash(key(rec)) % npartitions].append(rec)
        return dict(out)

    return splitter


def concat_records(shards: list) -> list:
    out: list = []
    for shard in shards:
        out.extend(shard)
    return out
