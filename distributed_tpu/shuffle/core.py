"""P2P shuffle engine (reference shuffle/_core.py, _worker_plugin.py).

All-to-all repartitioning that bypasses the task-graph data model:
N input partitions -> shards pushed directly worker->worker -> M output
partitions, at O(N+M) scheduler tasks instead of O(N*M)
(reference shuffle/_core.py:62-380).

Graph shape (built by ``distributed_tpu.shuffle.api``):

    transfer(i):  split input partition i by output -> push shards to the
                  owner of each output partition (batched direct RPC via
                  CommShardsBuffer)
    barrier:      after all transfers -> broadcast inputs_done to every
                  participant
    unpack(j):    restricted to worker_for[j] -> await inputs_done,
                  assemble output partition j from the spill store

Storage: received shards drain through a ``DiskShardsBuffer`` (spill
files per output partition) or ``MemoryShardsBuffer``, both throttled by
a ``ResourceLimiter`` — a shuffle can move far more data than fits in
memory (reference shuffle/_disk.py, _limiter.py:89).

Control plane: run specs are owned by the SCHEDULER extension
(``shuffle.scheduler_ext``), which assigns output partitions to workers
and bumps the ``run_id`` epoch on participating-worker loss or duplicate
output fetches, releasing the shuffle's tasks for recomputation
(reference shuffle/_scheduler_plugin.py:336-344).  Workers fence stale
epochs by run_id (reference shuffle/_worker_plugin.py:36).
"""

from __future__ import annotations

import asyncio
import logging
from collections import defaultdict
from typing import Any, Callable

from distributed_tpu import config
from distributed_tpu.exceptions import CommClosedError
from distributed_tpu.protocol.serialize import Serialize, unwrap
from distributed_tpu.shuffle.buffers import (
    CommShardsBuffer,
    DiskShardsBuffer,
    MemoryShardsBuffer,
    ResourceLimiter,
    ShuffleClosedError,
)

logger = logging.getLogger("distributed_tpu.shuffle")


class ShuffleSpec:
    """Declarative description of one shuffle run (reference
    shuffle/_core.py:421).  Created by the scheduler extension; run_id is
    the fencing epoch."""

    __slots__ = ("id", "run_id", "npartitions_out", "n_inputs", "worker_for",
                 "device_owned")

    def __init__(self, id: str, run_id: int, npartitions_out: int,
                 worker_for: dict[int, str], n_inputs: int | None = None,
                 device_owned: bool = False):
        self.id = id
        self.run_id = run_id
        self.npartitions_out = npartitions_out
        # worker_for pins partitions to pod device owners (multi-host
        # device plane): the barrier then fans the exchange out SPMD
        self.device_owned = bool(device_owned)
        # input-partition count is independent of the output fan-out
        # (n_in != n_out shuffles); consumers that need "how many
        # registrations complete the exchange" must use this, never
        # npartitions_out
        self.n_inputs = n_inputs if n_inputs is not None else npartitions_out
        self.worker_for = dict(worker_for)

    @property
    def participants(self) -> list[str]:
        return sorted(set(self.worker_for.values()))

    def to_msg(self) -> dict:
        return {
            "id": self.id,
            "run_id": self.run_id,
            "npartitions_out": self.npartitions_out,
            "n_inputs": self.n_inputs,
            "device_owned": self.device_owned,
            "worker_for": {str(k): v for k, v in self.worker_for.items()},
        }

    @classmethod
    def from_msg(cls, msg: dict) -> "ShuffleSpec":
        return cls(
            msg["id"], msg["run_id"], msg["npartitions_out"],
            {int(k): v for k, v in msg["worker_for"].items()},
            n_inputs=msg.get("n_inputs"),
            device_owned=msg.get("device_owned", False),
        )


class ShuffleRun:
    """Per-worker engine for one (id, run_id) (reference shuffle/_core.py:62)."""

    def __init__(self, spec: ShuffleSpec, worker: Any, *,
                 use_disk: bool | None = None,
                 memory_limit: int | None = None):
        self.spec = spec
        self.worker = worker
        self.inputs_done = asyncio.Event()
        self.closed = False
        # pipelined push plane: dedicated comm + serializing lock +
        # unacked-window counter per peer
        self._push_comms: dict[str, Any] = {}
        self._push_locks: defaultdict[str, asyncio.Lock] = defaultdict(
            asyncio.Lock
        )
        self._push_unacked: dict[str, int] = {}
        self._push_sent: defaultdict[str, int] = defaultdict(int)
        # built once: the spec message rides only the run-opening push
        # per peer (its worker_for map is O(workers) — at 128 workers,
        # re-walking it per push measurably dominated message handling)
        self._spec_msg = spec.to_msg()
        self.bytes_received = 0
        self.transfers_done: set[int] = set()
        self.outputs_served: set[int] = set()
        self.local_outputs_left = sum(
            1 for addr in spec.worker_for.values() if addr == worker.address
        )
        if use_disk is None:
            use_disk = bool(config.get("shuffle.disk"))
        if memory_limit is None:
            memory_limit = config.parse_bytes(config.get("shuffle.memory-limit"))
        self.limiter = ResourceLimiter(memory_limit)
        if use_disk:
            import tempfile

            directory = tempfile.mkdtemp(
                prefix=f"dtpu-shuffle-{spec.id}-r{spec.run_id}-"
            )
            self.store: Any = DiskShardsBuffer(directory, limiter=self.limiter)
        else:
            self.store = MemoryShardsBuffer(limiter=self.limiter)
        self.comms = CommShardsBuffer(
            send=self._send_to_peer,
            limiter=ResourceLimiter(memory_limit),
            message_bytes_limit=config.parse_bytes(
                config.get("shuffle.comm-message-bytes")
            ),
        )
        from distributed_tpu.utils.misc import time as _now

        self.last_activity = _now()

    def touch(self) -> None:
        from distributed_tpu.utils.misc import time as _now

        self.last_activity = _now()

    @property
    def id(self) -> str:
        return self.spec.id

    @property
    def run_id(self) -> int:
        return self.spec.run_id

    # ---------------------------------------------------------- data plane
    #
    # Pushes are PIPELINED one-way writes on a dedicated comm per peer:
    # the request-response-per-push design paid a full RPC round trip
    # for every (sender, receiver) pair — at 128x128 partitions that is
    # 16k round trips of pure control latency (measured: 86% of the
    # config-4 wall).  The server processes messages on one comm
    # strictly in order, so a single ``shuffle_receive_flush``
    # request-response at barrier time confirms every prior push on
    # that comm AND carries any deferred error (stale epoch, receive
    # failure).  Backpressure: a window of unacked pushes per peer
    # forces a flush round trip, and on TCP the receiver's blocked
    # handler propagates to the sender's write.

    PUSH_WINDOW = 16

    async def _push_comm(self, addr: str):
        comm = self._push_comms.get(addr)
        if comm is None or comm.closed:
            if self._push_unacked.get(addr, 0) > 0:
                # the comm died with pushes written but unconfirmed:
                # they may be lost, and the receiver's processed count
                # could never reach our sent count — fail the epoch NOW
                # instead of stalling the barrier to its timeout
                raise ShuffleClosedError(
                    f"{self.id}: push comm to {addr} died with "
                    f"{self._push_unacked[addr]} unconfirmed pushes"
                )
            from distributed_tpu.comm.core import connect

            comm = await connect(addr, **self.worker.connection_args)
            self._push_comms[addr] = comm
            self._push_unacked[addr] = 0
        return comm

    async def _push_flush_one(self, addr: str, comm: Any) -> None:
        """One flush round trip confirming every prior push on ``comm``."""
        await comm.write({
            "op": "shuffle_receive_flush",
            "id": self.id, "run_id": self.run_id, "reply": True,
        })
        resp = await comm.read()
        self._push_unacked[addr] = 0
        if resp.get("status") == "stale":
            raise ShuffleClosedError(
                f"{self.id} run {self.run_id} superseded on {addr}"
            )
        if resp.get("status") != "OK":
            raise RuntimeError(f"shuffle push failed on {addr}: {resp!r}")

    async def _send_to_peer(self, addr: str, shards: list) -> None:
        """CommShardsBuffer drain target: one batched push to one peer.
        ``shards`` is a list of (output_partition, tag, shard)."""
        by_output: defaultdict[int, list] = defaultdict(list)
        for j, tag, shard in shards:
            by_output[j].append((tag, shard))
        lock = self._push_locks[addr]
        async with lock:
            comm = await self._push_comm(addr)
            msg = {
                "op": "shuffle_receive",
                "id": self.id, "run_id": self.run_id,
                "shards": Serialize(dict(by_output)),
                "sender": self.worker.address,
                "reply": False,
            }
            if not self._push_sent[addr]:
                # run-opening push on this comm: carry the spec so a
                # cold receiver can build the run without a scheduler
                # round trip (in-order delivery per comm guarantees it
                # arrives first); later pushes stay lean
                msg["spec"] = self._spec_msg
            await comm.write(msg)
            self._push_sent[addr] += 1
            self._push_unacked[addr] += 1
            if self._push_unacked[addr] >= self.PUSH_WINDOW:
                await self._push_flush_one(addr, comm)

    async def add_partition(self, data: Any, partition_id: int,
                            splitter: Callable) -> int:
        """Split one input partition and push shards to their owners
        (reference shuffle/_core.py:331)."""
        if self.closed:
            raise ShuffleClosedError(self.id)
        self.touch()
        out_shards = splitter(data, self.spec.npartitions_out)
        local: defaultdict[int, list] = defaultdict(list)
        remote: defaultdict[str, list] = defaultdict(list)
        for j, shard in out_shards.items():
            j = int(j) % self.spec.npartitions_out
            addr = self.spec.worker_for[j]
            if addr == self.worker.address:
                local[j].append((partition_id, shard))
            else:
                remote[addr].append((j, partition_id, shard))
        if local:
            await self.receive(dict(local))
        if remote:
            await self.comms.write(dict(remote))
        self.transfers_done.add(partition_id)
        return partition_id

    async def receive(self, shards: dict) -> None:
        """Accept shards pushed by a peer: drain into the spill store
        (reference shuffle/_core.py:260)."""
        if self.closed:
            raise ShuffleClosedError(self.id)
        self.touch()
        data = {int(j): list(tagged) for j, tagged in shards.items()}
        # the store's write sizes every shard for its limiter booking —
        # reuse that instead of a second full sizeof walk
        self.bytes_received += await self.store.write(data)

    async def barrier(self) -> None:
        """All inputs transferred: route the barrier through the scheduler
        extension, which broadcasts inputs_done to EVERY participating
        worker (transfer-only ones included) and waits for each to flush
        its outbound shards before acknowledging (reference
        shuffle/_core.py:190, _scheduler_plugin.py:95).  Flushing only our
        own comms here would race unpack against other workers' in-flight
        shards."""
        await self.comms.flush()  # local head start; scheduler re-flushes
        try:
            resp = await self.worker.rpc(
                self.worker.scheduler_addr
            ).shuffle_barrier(id=self.id, run_id=self.run_id)
        except (CommClosedError, OSError) as e:
            raise RuntimeError("barrier could not reach scheduler") from e
        status = resp.get("status")
        if status == "stale":
            raise ShuffleClosedError(
                f"{self.id} run {self.run_id} superseded by {resp.get('run_id')}"
            )
        if status != "OK":
            raise ShuffleClosedError(
                f"{self.id} barrier failed: {resp.get('error', status)}"
            )

    async def collect_output(self, j: int, timeout: float = 30.0) -> list:
        """The deduped, tag-ordered shard list for output partition j
        (reference shuffle/_core.py:353).  Serves each partition exactly
        once: a second request means a recomputed unpack would get an
        empty partition, so the run fails for an epoch restart instead."""
        self.touch()
        if not self.inputs_done.is_set():
            # about to block on EXTERNAL progress (the barrier needs every
            # transfer to finish): leave the execution slot first, or a
            # dep-free recomputed unpack wedges a 1-thread worker whose
            # queue holds the very transfer the barrier is waiting for
            # (measured deadlock-until-timeout under epoch restarts)
            try:
                from distributed_tpu.client.worker_client import secede

                secede()
            except ValueError:
                pass  # rpc handler path (shuffle_fetch_output): no task slot
            await asyncio.wait_for(self.inputs_done.wait(), timeout)
        self.touch()
        if j in self.outputs_served:
            raise ShuffleClosedError(
                f"{self.id}: output partition {j} already served; "
                f"restart required"
            )
        self.outputs_served.add(j)
        tagged = await self.store.read(j)
        # dedupe by source tag: a transfer that ran twice (worker retry)
        # appended its shards twice; last write wins
        bucket: dict[Any, Any] = {}
        for tag, shard in tagged:
            bucket[tag] = shard
        self.local_outputs_left -= 1
        if self.local_outputs_left <= 0:
            self.worker.shuffle.schedule_cleanup(self.id, self.run_id)
        return [bucket[tag] for tag in sorted(bucket)]

    async def get_output_partition(self, j: int, assembler: Callable,
                                   timeout: float = 30.0) -> Any:
        """Assemble output partition j, fetching from its owner when this
        worker is not it (a recomputed unpack may have lost its worker
        restriction — reference pins unpacks via _set_restriction,
        _scheduler_plugin.py:281; the fetch fallback keeps mis-placed
        recomputes correct instead of silently empty)."""
        owner = self.spec.worker_for.get(int(j) % self.spec.npartitions_out)
        if owner == self.worker.address or owner is None:
            return assembler(await self.collect_output(j, timeout))
        resp = await self.worker.rpc(owner).shuffle_fetch_output(
            id=self.id, run_id=self.run_id, j=int(j)
        )
        if resp.get("status") != "OK":
            raise ShuffleClosedError(
                f"{self.id}: owner {owner} cannot serve partition {j}: "
                f"{resp.get('status')}"
            )
        return assembler(unwrap(resp["shards"]))

    def close(self) -> None:
        if self.closed:
            return
        self.closed = True
        for buf in (self.store, self.comms):
            self.worker._ongoing_background_tasks.call_soon(buf.close)
        for comm in self._push_comms.values():
            if not comm.closed:
                comm.abort()
        self._push_comms.clear()
        self._push_unacked.clear()


class ShuffleWorkerExtension:
    """Caches active runs by (id, run_id); fences stale epochs; fetches
    authoritative specs from the scheduler extension
    (reference shuffle/_worker_plugin.py:36)."""

    def __init__(self, worker: Any):
        self.worker = worker
        self.runs: dict[str, ShuffleRun] = {}  # id -> newest run
        self.RUN_TTL = config.parse_timedelta(config.get("shuffle.run-ttl"))
        # deferred outcomes of ONE-WAY pushes (reply=False messages have
        # nowhere to report): the sender's shuffle_receive_flush round
        # trip picks them up.  Bounded: epochs are short-lived.
        self._push_errors: dict[tuple[str, int], str] = {}
        # pushes PROCESSED per (id, run_id, sender): the barrier's
        # wait_pushes compares these against the senders' reported
        # counts — scheduler-aggregated confirmation instead of a flush
        # round trip per (sender, receiver) pair
        self._push_processed: defaultdict[tuple[str, int, str], int] = (
            defaultdict(int)
        )
        self._push_event = asyncio.Event()
        worker.handlers["shuffle_receive"] = self.shuffle_receive
        worker.handlers["shuffle_receive_flush"] = self.shuffle_receive_flush
        worker.handlers["shuffle_wait_pushes"] = self.shuffle_wait_pushes
        worker.handlers["shuffle_inputs_done"] = self.shuffle_inputs_done
        worker.handlers["shuffle_fetch_output"] = self.shuffle_fetch_output
        worker.handlers["device_shuffle_exchange"] = self.device_exchange
        worker.handlers["device_shuffle_precheck"] = self.device_precheck

    async def device_precheck(self, id: str = "", run_id: int = 0) -> dict:
        from distributed_tpu.shuffle.device import (
            device_shuffle_precheck_handler,
        )

        return await device_shuffle_precheck_handler(
            self.worker, id=id, run_id=run_id
        )

    async def device_exchange(self, id: str = "", run_id: int = 0,
                              max_n: int = 0) -> dict:
        """Join a device-plane exchange epoch with this process's local
        shards (multi-host SPMD; shuffle/device.py)."""
        from distributed_tpu.shuffle.device import (
            device_shuffle_exchange_handler,
        )

        return await device_shuffle_exchange_handler(
            self.worker, id=id, run_id=run_id, max_n=max_n
        )

    def get_or_create(self, spec: ShuffleSpec) -> ShuffleRun:
        run = self.runs.get(spec.id)
        if run is not None:
            if run.run_id > spec.run_id:
                raise ShuffleClosedError(
                    f"{spec.id} run {spec.run_id} superseded by {run.run_id}"
                )
            if run.run_id == spec.run_id:
                run.touch()
                return run
            run.close()  # stale epoch: replace
        run = self.runs[spec.id] = ShuffleRun(spec, self.worker)
        # TTL backstop: runs whose outputs are never unpacked (transfer-only
        # workers, cancelled shuffles) must not accumulate forever
        self.schedule_cleanup(spec.id, spec.run_id, delay=self.RUN_TTL)
        return run

    async def get_or_create_remote(self, shuffle_id: str) -> ShuffleRun:
        """Authoritative path for task bodies: ask the scheduler for the
        CURRENT epoch's spec (a restarted shuffle has a bumped run_id)."""
        resp = await self.worker.rpc(self.worker.scheduler_addr).shuffle_get_run(
            id=shuffle_id, worker=self.worker.address
        )
        if resp.get("status") != "OK":
            raise ShuffleClosedError(
                f"scheduler does not know shuffle {shuffle_id}: {resp!r}"
            )
        return self.get_or_create(ShuffleSpec.from_msg(resp["spec"]))

    def _get_checked(self, id: str, run_id: int) -> ShuffleRun | None:
        run = self.runs.get(id)
        if run is None or run.run_id != run_id:
            return None
        return run

    # ------------------------------------------------------------ handlers

    async def shuffle_receive(self, id: str = "", run_id: int = 0,
                              spec: dict | None = None,
                              shards: Any = None,
                              sender: str = "") -> dict:
        """Accept a shard push.  Request-response callers read the
        status directly; pipelined one-way pushes (reply=False) get
        their non-OK outcomes recorded for shuffle_receive_flush."""
        def _fail(status: str) -> dict:
            self._push_errors[(id, run_id)] = status
            return {"status": status, "id": id, "run_id": run_id}

        try:
            run = self.runs.get(id)
            if run is not None and run.run_id > run_id:
                return _fail("stale")
            if run is None or run.run_id < run_id:
                # first contact for this (id, run_id): build the run
                # from the spec riding on the run-opening push, or — if
                # this push raced ahead of it (reconnected comm) — from
                # the scheduler
                if spec is not None:
                    run = self.get_or_create(ShuffleSpec.from_msg(spec))
                else:
                    try:
                        run = await self.get_or_create_remote(id)
                    except Exception:
                        return _fail("unknown-run")
                    if run.run_id > run_id:
                        return _fail("stale")
                    if run.run_id < run_id:
                        return _fail("unknown-run")
            await run.receive(unwrap(shards))
        except ShuffleClosedError:
            return _fail("stale")
        except Exception as exc:
            # one-way pushes (reply=False) have NOWHERE to report: an
            # exception escaping to the rpc loop is silently dropped and
            # the barrier would only see a 60s wait_pushes timeout.
            # Record the real cause for the flush/wait round instead.
            logger.exception("shuffle push failed (%s run %s)", id, run_id)
            return _fail(f"receive-failed: {exc!r}"[:300])
        if sender:
            self._push_processed[(id, run_id, sender)] += 1
            self._push_event.set()
        return {"status": "OK"}

    async def shuffle_wait_pushes(self, id: str = "", run_id: int = 0,
                                  expected: dict | None = None,
                                  timeout: float = 60.0) -> dict:
        """Barrier confirmation: wait until this worker has PROCESSED
        at least ``expected[sender]`` pushes from each sender (their
        self-reported counts, aggregated by the scheduler).  One RPC per
        receiver replaces a flush round trip per (sender, receiver)
        pair — 16k round trips became 2 per worker at 128x128."""
        expected = expected or {}
        deadline = asyncio.get_event_loop().time() + timeout
        while True:
            err = self._push_errors.get((id, run_id))
            if err is not None:
                return {"status": err, "id": id, "run_id": run_id}
            run = self.runs.get(id)
            if run is not None and run.run_id > run_id:
                return {"status": "stale", "id": id, "run_id": run_id}
            missing = {
                s: n for s, n in expected.items()
                if self._push_processed[(id, run_id, s)] < n
            }
            if not missing:
                return {"status": "OK"}
            if asyncio.get_event_loop().time() > deadline:
                return {"status": "timeout", "missing": missing}
            self._push_event.clear()
            try:
                await asyncio.wait_for(
                    self._push_event.wait(),
                    max(deadline - asyncio.get_event_loop().time(), 0.01),
                )
            except asyncio.TimeoutError:
                pass

    async def shuffle_receive_flush(self, id: str = "",
                                    run_id: int = 0) -> dict:
        """Settle a peer's pipelined pushes: the server processes one
        comm's messages in order, so by the time this runs every prior
        push on the same comm has been handled — report any deferred
        failure, or staleness discovered since."""
        err = self._push_errors.get((id, run_id))
        if err is not None:
            return {"status": err, "id": id, "run_id": run_id}
        run = self.runs.get(id)
        if run is not None and run.run_id > run_id:
            return {"status": "stale", "id": id, "run_id": run_id}
        return {"status": "OK"}

    async def shuffle_fetch_output(self, id: str = "", run_id: int = 0,
                                   j: int = 0) -> dict:
        """Serve an output partition's shards to a mis-placed unpack."""
        run = self._get_checked(id, run_id)
        if run is None:
            return {"status": "stale", "id": id, "run_id": run_id}
        try:
            shards = await run.collect_output(j)
        except ShuffleClosedError:
            return {"status": "closed", "id": id, "run_id": run_id}
        except asyncio.TimeoutError:
            return {"status": "timeout", "id": id, "run_id": run_id}
        return {"status": "OK", "shards": Serialize(shards)}

    async def shuffle_inputs_done(self, id: str = "", run_id: int = 0,
                                  spec: dict | None = None) -> dict:
        run = self._get_checked(id, run_id)
        if run is None:
            if spec is None:
                return {"status": "stale"}
            try:
                run = self.get_or_create(ShuffleSpec.from_msg(spec))
            except ShuffleClosedError:
                return {"status": "stale"}
        # drain OUR outbound shards onto the wire before acknowledging,
        # and report how many pushes went to each peer: the scheduler
        # aggregates the counts and asks every RECEIVER to confirm
        # processing in ONE wait_pushes RPC (reference _core.py:272
        # flushes inside inputs_done; per-pair flush round trips were
        # 60% of the 128x128 shuffle wall)
        await run.comms.flush()
        run.inputs_done.set()
        return {"status": "OK", "sent": dict(run._push_sent)}

    def schedule_cleanup(self, id: str, run_id: int, delay: float = 30.0) -> None:
        """Forget a run after a grace period; reschedules while active."""

        async def _cleanup() -> None:
            from distributed_tpu.utils.misc import time as _now

            run = self.runs.get(id)
            if run is None or run.run_id != run_id:
                return
            idle = _now() - run.last_activity
            # idleness required even with no local outputs left: a
            # transfer-only worker is still actively pushing shards
            if (run.local_outputs_left <= 0 and idle >= 5.0) or idle >= self.RUN_TTL:
                run.close()
                del self.runs[id]
                # per-epoch push bookkeeping dies with the run, or a
                # long-lived worker leaks one entry per (epoch, sender)
                self._push_errors.pop((id, run_id), None)
                for k in [
                    k for k in self._push_processed
                    if k[0] == id and k[1] <= run_id
                ]:
                    del self._push_processed[k]
                # collect any device-resident run of this epoch too:
                # abandoned epochs must not pin device arrays.  Idle-gated
                # because the device store is process-global while this
                # cleanup fires off ONE worker's host-run idleness — a
                # live exchange other workers are unpacking stays.
                from distributed_tpu.shuffle.device import device_store

                device_store().forget(id, run_id,
                                      only_idle_for=self.RUN_TTL)
            else:
                self.schedule_cleanup(
                    id, run_id, delay=max(self.RUN_TTL - idle, 5.0)
                )

        self.worker._ongoing_background_tasks.call_later(delay, _cleanup)

    def close(self) -> None:
        for run in self.runs.values():
            run.close()
        self.runs.clear()


# ------------------------------------------------------------ splitters

def stable_hash(x: Any) -> int:
    """Process-independent hash: builtin hash() is randomized per
    interpreter for str/bytes, which would route equal keys hashed on
    different workers to different partitions."""
    import hashlib

    if isinstance(x, bool):
        x = repr(x).encode()
    elif isinstance(x, int):
        return x
    if isinstance(x, str):
        x = x.encode()
    elif not isinstance(x, bytes):
        x = repr(x).encode()
    return int.from_bytes(
        hashlib.blake2b(x, digest_size=8).digest(), "big"
    )


def split_records_by_hash(data: Any, npartitions: int) -> dict[int, list]:
    """Generic record splitter: hash each record (or its key for
    (key, value) pairs is the caller's concern) into an output partition."""
    out: defaultdict[int, list] = defaultdict(list)
    for rec in data:
        out[stable_hash(rec) % npartitions].append(rec)
    return dict(out)


def make_keyed_splitter(key: Callable) -> Callable:
    def splitter(data: Any, npartitions: int) -> dict[int, list]:
        out: defaultdict[int, list] = defaultdict(list)
        for rec in data:
            out[stable_hash(key(rec)) % npartitions].append(rec)
        return dict(out)

    return splitter


def concat_records(shards: list) -> list:
    out: list = []
    for shard in shards:
        out.extend(shard)
    return out
