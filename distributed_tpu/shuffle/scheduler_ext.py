"""Scheduler-side shuffle control plane (reference
shuffle/_scheduler_plugin.py).

Owns the authoritative run spec per shuffle id:

- assigns output partitions to workers round-robin over the running
  workers (reference _calculate_worker_for, _scheduler_plugin.py:182);
- hands the CURRENT epoch's spec to task bodies via the
  ``shuffle_get_run`` RPC (workers never trust a spec baked into the
  graph — it may predate a restart);
- on participating-worker loss or a duplicate output fetch, bumps the
  ``run_id`` epoch, reassigns output partitions over the surviving
  workers, rewrites the unpack tasks' worker restrictions, and releases
  the shuffle's transfer/barrier/unpack tasks so the whole run is
  recomputed under the new epoch (reference remove_worker /
  _restart_shuffle, _scheduler_plugin.py:336-344).
"""

from __future__ import annotations

import logging
from typing import Any

from distributed_tpu import config
from distributed_tpu.exceptions import P2PShuffleError
from distributed_tpu.utils.misc import seq_name

logger = logging.getLogger("distributed_tpu.shuffle")


class ShuffleState:
    __slots__ = ("id", "run_id", "npartitions_out", "n_inputs", "worker_for",
                 "participants", "attempts", "device_owned", "wants_device")

    def __init__(self, id: str, run_id: int, npartitions_out: int,
                 n_inputs: int, worker_for: dict[int, str]):
        self.id = id
        self.run_id = run_id
        self.npartitions_out = npartitions_out
        self.n_inputs = n_inputs
        self.worker_for = worker_for
        # every worker that touched this epoch (transfer-only workers
        # included) — the barrier must flush ALL of them, not just output
        # owners (reference _scheduler_plugin.py:95)
        self.participants: set[str] = set()
        # consecutive epoch restarts without a completed barrier: bounded
        # by shuffle.max-restarts, reset on barrier success
        self.attempts = 0
        # worker_for came from pod device ownership (multihost plane);
        # wants_device records that the graph builder asked for it, so
        # epoch restarts recompute the same way
        self.device_owned = False
        self.wants_device = False

    @property
    def all_workers(self) -> set[str]:
        return self.participants | set(self.worker_for.values())

    def to_msg(self) -> dict:
        return {
            "id": self.id,
            "run_id": self.run_id,
            "npartitions_out": self.npartitions_out,
            "n_inputs": self.n_inputs,
            "device_owned": self.device_owned,
            "worker_for": {str(k): v for k, v in self.worker_for.items()},
        }


class ShuffleSchedulerExtension:
    """Registered as ``extensions['shuffle']`` (reference
    DEFAULT_EXTENSIONS, scheduler.py:178-193)."""

    def __init__(self, scheduler: Any):
        self.scheduler = scheduler
        self.active: dict[str, ShuffleState] = {}
        # restart coalescing: worker departures arrive one remove_worker
        # call at a time even when a whole scale-down leaves together; a
        # debounce window turns N departures into ONE epoch restart
        # (reference _scheduler_plugin.py:336-344 restarts per event)
        self._pending_restarts: dict[str, str] = {}  # id -> first reason
        self.max_restarts = int(config.get("shuffle.max-restarts") or 0)
        self.restart_debounce = config.parse_timedelta(
            config.get("shuffle.restart-debounce")
        )
        scheduler.handlers.update(
            {
                "shuffle_get_or_create": self.handle_get_or_create,
                "shuffle_get_run": self.handle_get_run,
                "shuffle_restart": self.handle_restart,
                "shuffle_barrier": self.handle_barrier,
            }
        )

    # ------------------------------------------------------------ helpers

    def _calculate_worker_for(self, npartitions_out: int,
                              device: bool = False) -> tuple[dict[int, str], bool]:
        """Map output partitions to workers.

        Device-ownership mode: when workers joined a pod-wide jax
        runtime (``--jax-coordinator``) they registered their global
        mesh device indices; if those DISJOINTLY cover partitions
        0..n-1, partition j is pinned to the process owning mesh device
        j — the device data plane then never moves a shard off its
        chips.  Otherwise: round-robin over sorted running workers
        (reference _scheduler_plugin.py:182).  Returns
        ``(worker_for, device_owned)``."""
        state = self.scheduler.state
        if device:
            # ONLY device-plane shuffles ask for ownership mapping: a
            # host-object shuffle must keep spreading over the whole
            # cluster (ownership would concentrate every partition on
            # the pod workers)
            owners: dict[int, str] = {}
            disjoint = True
            for ws in state.running:
                for d in ws.extra.get("jax_devices") or ():
                    if d in owners:
                        disjoint = False
                    owners[int(d)] = ws.address
            if (
                disjoint
                and owners
                and all(j in owners for j in range(npartitions_out))
            ):
                return {j: owners[j] for j in range(npartitions_out)}, True
        addrs = sorted(ws.address for ws in state.running)
        if not addrs:
            addrs = sorted(state.workers)
        if not addrs:
            raise RuntimeError("no workers available for shuffle")
        return {j: addrs[j % len(addrs)] for j in range(npartitions_out)}, False

    def _task_keys(self, st: ShuffleState) -> list[str]:
        """Insertion order matters: the transition engine drains
        recommendations LIFO (``dict.popitem``), so listing transfers
        first and unpacks last makes DEPENDENTS transition first —
        releasing a producer before its processing dependent would trip
        the scheduler's dep-missing invariant mid-drain."""
        keys = [f"{st.id}-transfer-{i}" for i in range(st.n_inputs)]
        keys.append(f"{st.id}-barrier")
        keys.extend(f"{st.id}-unpack-{j}" for j in range(st.npartitions_out))
        return keys

    def _pin_tasks_home(self, st: ShuffleState) -> None:
        """Exempt this shuffle's tasks from work stealing (``ts.homed``,
        same flag the partition planner uses).  A transfer splits ITS
        OWN input partition in place and unpack is restriction-pinned to
        its output owner: stealing either moves megabytes to save
        milliseconds, and on top of the locality damage the stealable
        backlog they create was measured dragging the DEVICE balance
        kernel into every tick of a 128-worker shuffle (~24% of e2e
        wall went to deciding not to steal)."""
        tasks = self.scheduler.state.tasks
        stealing = getattr(
            self.scheduler.state, "extensions", {}
        ).get("stealing")
        for key in self._task_keys(st):
            ts = tasks.get(key)
            if ts is not None:
                # "pin", not "plan": the flag stays truthy for the
                # steal exemption, but the decision ledger must not
                # attribute shuffle pins to the jax partition planner
                # (ts.homed carries provenance; state.py TaskState)
                ts.homed = "pin"
                if stealing is not None:
                    # already-queued tasks entered stealable before the
                    # first worker registered this shuffle: purge them,
                    # or they keep tripping the device-balance gate
                    stealing.remove_key_from_stealable(ts)

    def _closing(self) -> bool:
        return (
            self.scheduler.status.name in ("closing", "closed")
            or getattr(self.scheduler, "draining", False)
        )

    def _request_restart(self, st: ShuffleState, reason: str) -> None:
        """Coalescing entry point for every restart cause (worker loss,
        barrier failure, worker-requested): causes arriving within the
        debounce window restart the epoch ONCE, and repeated restarts
        back off exponentially."""
        if self._closing():
            return
        if st.id in self._pending_restarts:
            return  # already scheduled: this cause rides along
        self._pending_restarts[st.id] = reason
        delay = min(
            self.restart_debounce * (2 ** min(st.attempts, 6)), 2.0
        )
        # per-shuffle timer: a shared drain would let shuffle B's short
        # debounce fire shuffle A's restart early, collapsing A's backoff
        self.scheduler._ongoing_background_tasks.call_later(
            delay, self._drain_restart, st.id
        )

    async def _drain_restart(self, id: str) -> None:
        reason = self._pending_restarts.pop(id, None)
        if reason is None or self._closing():
            return
        st = self.active.get(id)
        if st is None:
            return
        st.attempts += 1
        if self.max_restarts and st.attempts > self.max_restarts:
            self._fail(st, reason)
        else:
            self._restart(st, reason)

    def _fail(self, st: ShuffleState, reason: str) -> None:
        """Restart budget exhausted: err the shuffle's output tasks so
        clients get a P2PShuffleError instead of an endless restart storm."""
        logger.error(
            "shuffle %s failed after %d restarts (%s)",
            st.id, st.attempts - 1, reason,
        )
        self.active.pop(st.id, None)
        state = self.scheduler.state
        exc = P2PShuffleError(
            f"shuffle {st.id} failed after {st.attempts - 1} restarts: "
            f"{reason}"
        )
        recs: dict[str, str] = {}
        for k in self._task_keys(st):
            ts = state.tasks.get(k)
            if ts is None or ts.state in ("erred", "forgotten"):
                continue
            # preset the blame so any-state -> erred composes through
            # released (state._transition routes untable'd pairs there,
            # and _transition_waiting_released checks exception_blame
            # before resurrecting a wanted task)
            ts.exception = exc
            ts.exception_text = str(exc)
            ts.exception_blame = ts
            if state.native is not None:  # blame flag lives in the SoA
                state.native.mark_task(ts)
            recs[k] = "erred"
        if recs:
            stimulus_id = seq_name("shuffle-failed")
            client_msgs, worker_msgs = state.transitions(recs, stimulus_id)
            self.scheduler.send_all(client_msgs, worker_msgs)

    def _restart(self, st: ShuffleState, reason: str) -> None:
        st.run_id += 1
        try:
            st.worker_for, st.device_owned = self._calculate_worker_for(
                st.npartitions_out, device=st.wants_device
            )
        except RuntimeError:
            # no workers left (cluster draining): the shuffle cannot be
            # recomputed now; drop it so task bodies get unknown-shuffle
            # and reschedule when workers return
            logger.warning("shuffle %s unrecoverable (%s): no workers", st.id, reason)
            self.active.pop(st.id, None)
            return
        st.participants = set()  # re-registered as the new epoch's tasks run
        logger.warning(
            "shuffle %s restarting as run %d (%s)", st.id, st.run_id, reason
        )
        state = self.scheduler.state
        # retarget unpack restrictions at the new owners
        for j, addr in st.worker_for.items():
            ts = state.tasks.get(f"{st.id}-unpack-{j}")
            if ts is not None:
                ts.worker_restrictions = {addr}
                if state.native is not None:  # restriction flag -> SoA
                    state.native.mark_task(ts)
        # release the whole pipeline for recomputation under the new epoch
        recs = {
            k: "released"
            for k in self._task_keys(st)
            if k in state.tasks and state.tasks[k].state != "released"
        }
        if recs:
            stimulus_id = seq_name("shuffle-restart")
            client_msgs, worker_msgs = state.transitions(recs, stimulus_id)
            self.scheduler.send_all(client_msgs, worker_msgs)
        # releasing clears ts.homed: re-exempt the new epoch's tasks
        self._pin_tasks_home(st)

    # ----------------------------------------------------------- handlers

    async def handle_get_or_create(
        self, id: str = "", npartitions_out: int = 0, n_inputs: int = 0,
        worker: str = "", device: bool = False, **kwargs: Any,
    ) -> dict:
        st = self.active.get(id)
        if st is None:
            worker_for, device_owned = self._calculate_worker_for(
                npartitions_out, device=device
            )
            st = self.active[id] = ShuffleState(
                id, 1, npartitions_out, n_inputs, worker_for,
            )
            st.device_owned = device_owned
            st.wants_device = bool(device)
            self._pin_tasks_home(st)
        if worker:
            st.participants.add(worker)
        return {"status": "OK", "spec": st.to_msg(),
                "device_owned": st.device_owned}

    async def handle_get_run(self, id: str = "", worker: str = "",
                             **kwargs: Any) -> dict:
        st = self.active.get(id)
        if st is None:
            return {"status": "unknown-shuffle", "id": id}
        if worker:
            st.participants.add(worker)
        return {"status": "OK", "spec": st.to_msg()}

    async def handle_barrier(self, id: str = "", run_id: int = 0,
                             **kwargs: Any) -> dict:
        """Broadcast inputs_done to EVERY participating worker (transfer
        and unpack) and wait for each to flush its outbound shard buffer
        before acknowledging — only then may the barrier task complete and
        unpacks start reading (reference _scheduler_plugin.py:95,
        _core.py:272)."""
        import asyncio

        st = self.active.get(id)
        if st is None:
            return {"status": "unknown-shuffle", "id": id}
        if run_id != st.run_id:
            return {"status": "stale", "id": id, "run_id": st.run_id}
        spec = st.to_msg()

        async def one(addr: str):
            resp = await self.scheduler.rpc(addr).shuffle_inputs_done(
                id=id, run_id=run_id, spec=spec
            )
            if resp.get("status") != "OK":
                raise RuntimeError(
                    f"inputs_done rejected by {addr}: {resp!r}"
                )
            return addr, resp.get("sent") or {}

        results = await asyncio.gather(
            *(one(a) for a in sorted(st.all_workers)), return_exceptions=True
        )
        failures = [r for r in results if isinstance(r, BaseException)]
        if not failures:
            # round 2: every RECEIVER confirms it processed the pushes
            # the senders reported — the scheduler aggregates the counts
            # so confirmation costs ONE rpc per worker instead of a
            # flush round trip per (sender, receiver) pair
            expected: dict[str, dict[str, int]] = {}
            for addr, sent in results:
                for peer, n in sent.items():
                    expected.setdefault(peer, {})[addr] = int(n)

            async def confirm(addr: str):
                resp = await self.scheduler.rpc(addr).shuffle_wait_pushes(
                    id=id, run_id=run_id, expected=expected.get(addr) or {}
                )
                if resp.get("status") != "OK":
                    raise RuntimeError(
                        f"push confirmation failed on {addr}: {resp!r}"
                    )

            res2 = await asyncio.gather(
                *(confirm(a) for a in sorted(expected)),
                return_exceptions=True,
            )
            failures = [r for r in res2 if isinstance(r, BaseException)]
        if failures:
            # a participant died or went stale mid-barrier: restart the
            # epoch rather than serve partial outputs
            if run_id == st.run_id:
                self._request_restart(st, f"barrier failed: {failures[0]!r}")
            # NOT "status": "error" — that is the RPC layer's reserved
            # pickled-exception envelope (raise_remote_error); the task
            # body maps any non-OK status to ShuffleClosedError itself
            return {"status": "barrier-failed", "error": repr(failures[0])}
        st.attempts = 0  # a completed barrier proves the epoch is healthy
        return {"status": "OK", "run_id": run_id}

    async def handle_restart(self, id: str = "", run_id: int = 0,
                             **kwargs: Any) -> dict:
        """A worker hit a fatal run condition (e.g. duplicate output
        fetch): restart iff the reported epoch is still current."""
        st = self.active.get(id)
        if st is None:
            return {"status": "unknown-shuffle", "id": id}
        if run_id == st.run_id:
            self._request_restart(st, f"worker-requested (run {run_id})")
        return {"status": "OK", "run_id": st.run_id}

    # ------------------------------------------------- scheduler callbacks

    def remove_worker(self, scheduler: Any, address: str) -> None:
        """Participating worker died: every shuffle it owned outputs for
        or held transfer state for restarts under a new epoch
        (reference _scheduler_plugin.py:344)."""
        if self._closing():
            # cluster shutdown: workers leave one by one — restarting
            # each active shuffle per departure is noise, not recovery
            self.active.clear()
            self._pending_restarts.clear()
            return
        for st in list(self.active.values()):
            if address in st.all_workers:
                self._request_restart(st, f"lost worker {address}")

    def forget(self, id: str) -> None:
        self.active.pop(id, None)

    def close(self) -> None:
        """Scheduler shutdown: abandon active runs and pending restarts —
        departures during close must not spawn recovery work."""
        self.active.clear()
        self._pending_restarts.clear()
