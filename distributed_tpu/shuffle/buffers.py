"""Shuffle storage layer: shard buffers with spill-to-disk, batched
outbound comms, and memory backpressure.

Equivalents of the reference's shuffle buffering stack (re-designed for
asyncio, not copied):

- ``ResourceLimiter``   — reference shuffle/_limiter.py:89
- ``ShardsBuffer`` base — reference shuffle/_buffer.py
- ``MemoryShardsBuffer``— reference shuffle/_memory.py
- ``DiskShardsBuffer``  — reference shuffle/_disk.py (append-only spill
  files per output partition, read back at unpack time)
- ``CommShardsBuffer``  — reference shuffle/_comms.py (batches outbound
  shards per destination worker)

Writers block (``await``) while the limiter is over budget, so a shuffle
can move arbitrarily more data than fits in memory: received shards
drain to disk, outbound shards drain onto the wire, and ``add_partition``
simply slows down to match.
"""

from __future__ import annotations

import asyncio
import logging
import os
import pickle
import struct
from collections import defaultdict
from typing import Any, Awaitable, Callable

logger = logging.getLogger("distributed_tpu.shuffle")


class ShuffleClosedError(RuntimeError):
    """The shuffle run (or one of its buffers) was torn down; task bodies
    catch this and request an epoch restart (shuffle/api.py)."""


class ResourceLimiter:
    """Async budget meter: ``acquire`` blocks while over the limit
    (reference shuffle/_limiter.py:89 semantics)."""

    def __init__(self, limit: int | None):
        self.limit = limit
        self.acquired = 0
        self._event = asyncio.Event()
        self._event.set()

    def free(self) -> bool:
        return self.limit is None or self.acquired < self.limit

    def book(self, n: int) -> None:
        """Synchronously record n units as held (may overshoot the limit;
        progress beats strictness for shards larger than the budget)."""
        self.acquired += n
        if not self.free():
            self._event.clear()

    async def wait_free(self) -> None:
        """Block until the meter is back under its limit."""
        while not self.free():
            await self._event.wait()

    async def acquire(self, n: int) -> None:
        """Wait for headroom, then book n units."""
        await self.wait_free()
        self.book(n)

    def release(self, n: int) -> None:
        self.acquired -= n
        if self.acquired < 0:
            logger.warning("ResourceLimiter released below zero")
            self.acquired = 0
        if self.free():
            self._event.set()

    def __repr__(self) -> str:
        return f"<ResourceLimiter {self.acquired}/{self.limit}>"


def _nbytes(obj: Any) -> int:
    from distributed_tpu.utils.sizeof import sizeof

    return sizeof(obj)


class ShardsBuffer:
    """Accepts ``{id: [shards]}`` writes, drains them to ``_process``
    through a background flusher, largest bucket first (reference
    shuffle/_buffer.py shape).

    Subclasses implement ``async _process(id, shards)``; the limiter
    budget covers shards accepted but not yet processed.
    """

    def __init__(self, limiter: ResourceLimiter | None = None,
                 concurrency: int = 2):
        self.limiter = limiter or ResourceLimiter(None)
        self.shards: defaultdict[Any, list] = defaultdict(list)
        self.sizes: defaultdict[Any, int] = defaultdict(int)
        self.bytes_total = 0
        self.bytes_written = 0
        self._inflight = 0
        self._wake = asyncio.Event()
        self._done = asyncio.Event()
        self._done.set()
        self._exception: BaseException | None = None
        self.closed = False
        self._tasks = [
            asyncio.create_task(
                self._drain_loop(), name=f"shards-buffer-drain-{i}"
            )
            for i in range(concurrency)
        ]

    async def _process(self, id: Any, shards: list) -> None:
        raise NotImplementedError

    async def write(self, data: dict[Any, list]) -> int:
        """Accept shards; blocks while the limiter is over budget.
        Returns the booked byte estimate (callers reuse it instead of
        re-walking the shard structure)."""
        if self._exception is not None:
            raise self._exception
        if self.closed:
            raise ShuffleClosedError("buffer closed")
        total = 0
        for id, shards in data.items():
            if not shards:
                continue
            n = _nbytes(shards)
            total += n
            self.shards[id].extend(shards)
            self.sizes[id] += n
        if not total:
            return 0
        self.bytes_total += total
        self._done.clear()
        # book BEFORE waking the drainer (its release must never precede
        # the booking), then apply backpressure
        self.limiter.book(total)
        self._wake.set()
        await self.limiter.wait_free()
        # the buffer may have been torn down while we were blocked on
        # backpressure (epoch restart, run TTL): fail rather than report
        # shards accepted that were in fact dropped
        if self._exception is not None:
            raise self._exception
        if self.closed:
            raise ShuffleClosedError("buffer closed while writing")
        return total

    async def _drain_loop(self) -> None:
        while True:
            while not self.shards:
                if self.closed:
                    return
                self._wake.clear()
                if not self.shards and not self._inflight:
                    self._done.set()
                try:
                    await asyncio.wait_for(self._wake.wait(), 0.5)
                except asyncio.TimeoutError:
                    continue
            # largest bucket first keeps spill files chunky
            id = max(self.sizes, key=self.sizes.__getitem__)
            shards = self.shards.pop(id)
            size = self.sizes.pop(id)
            self._inflight += 1
            try:
                await self._process(id, shards)
                self.bytes_written += size
            except Exception as e:  # surfaced on next write/flush
                logger.exception("shard buffer process failed")
                self._exception = e
                self.closed = True
            finally:
                self._inflight -= 1
                self.limiter.release(size)
                if not self.shards and not self._inflight:
                    self._done.set()

    async def flush(self) -> None:
        """Wait until every accepted shard has been processed."""
        self._wake.set()
        await self._done.wait()
        if self._exception is not None:
            raise self._exception
        if self.closed:
            raise ShuffleClosedError("buffer closed")

    async def close(self) -> None:
        self.closed = True
        self._wake.set()
        for t in self._tasks:
            t.cancel()
        for t in self._tasks:
            try:
                await t
            except (asyncio.CancelledError, Exception):
                pass
        self._tasks = []
        # shards booked but never drained: release their budget so
        # writers blocked on backpressure wake up (and then observe
        # `closed` and raise), and unblock any flush() waiters — without
        # this, a transfer body awaiting wait_free() on a torn-down run
        # sleeps forever, wedging its execution slot (the round-3
        # mid-shuffle worker-loss hang)
        pending = sum(self.sizes.values())
        self.shards.clear()
        self.sizes.clear()
        if pending:
            self.limiter.release(pending)
        self._done.set()


class MemoryShardsBuffer(ShardsBuffer):
    """Keeps everything in memory (small shuffles / tests)
    (reference shuffle/_memory.py)."""

    def __init__(self, limiter: ResourceLimiter | None = None):
        super().__init__(limiter=limiter, concurrency=1)
        self._store: defaultdict[Any, list] = defaultdict(list)

    async def _process(self, id: Any, shards: list) -> None:
        self._store[id].extend(shards)

    async def read(self, id: Any) -> list:
        await self.flush()
        return self._store.pop(id, [])


class DiskShardsBuffer(ShardsBuffer):
    """Append-only spill file per output partition (reference
    shuffle/_disk.py).  Each record is a protocol-5 pickle with its
    out-of-band buffers stored as separate length-prefixed frames —
    ``[u64 n_frames][u64 len]*n [frames...]`` — so array payloads are
    written without being re-copied into the pickle stream and read
    back as zero-copy views of one file blob.  File IO runs in a thread
    so the event loop never blocks on disk."""

    def __init__(self, directory: str,
                 limiter: ResourceLimiter | None = None):
        super().__init__(limiter=limiter, concurrency=2)
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self._locks: defaultdict[Any, asyncio.Lock] = defaultdict(asyncio.Lock)

    def _path(self, id: Any) -> str:
        return os.path.join(self.directory, f"{id}.shards")

    async def _process(self, id: Any, shards: list) -> None:
        from distributed_tpu.protocol.serialize import pickle_oob_frames

        pieces: list = []
        for s in shards:
            buffers: list = []
            data = pickle.dumps(s, protocol=5, buffer_callback=buffers.append)
            frames = [data] + pickle_oob_frames(buffers)
            lengths = [memoryview(f).nbytes for f in frames]
            pieces.append(
                struct.pack(f"<{1 + len(frames)}Q", len(frames), *lengths)
            )
            pieces.extend(frames)
        async with self._locks[id]:
            await asyncio.get_running_loop().run_in_executor(
                None, self._append, self._path(id), pieces
            )

    @staticmethod
    def _append(path: str, pieces: list) -> None:
        with open(path, "ab") as f:
            for p in pieces:
                f.write(p)

    async def read(self, id: Any) -> list:
        """All shards spilled for this partition (flushes first)."""
        await self.flush()
        async with self._locks[id]:
            return await asyncio.get_running_loop().run_in_executor(
                None, self._read_sync, self._path(id)
            )

    @staticmethod
    def _read_sync(path: str) -> list:
        if not os.path.exists(path):
            return []
        out = []
        # read into a mutable blob: shards reconstruct as writable views
        # (the in-band pickle path returned writable copies — a consumer
        # mutating a shard in place must not fail only when it spilled)
        size = os.path.getsize(path)
        data = bytearray(size)
        with open(path, "rb") as f:
            n = f.readinto(data)
        if n != size:
            del data[n:]
        mv = memoryview(data)
        off = 0
        while off < len(data):
            (n_frames,) = struct.unpack_from("<Q", data, off)
            off += 8
            lengths = struct.unpack_from(f"<{n_frames}Q", data, off)
            off += 8 * n_frames
            frames = []
            for n in lengths:
                frames.append(mv[off : off + n])
                off += n
            # buffers deserialize as views of the one file blob
            out.append(pickle.loads(frames[0], buffers=frames[1:]))
        return out

    async def close(self) -> None:
        await super().close()
        try:
            for name in os.listdir(self.directory):
                if name.endswith(".shards"):
                    os.unlink(os.path.join(self.directory, name))
            os.rmdir(self.directory)
        except OSError:
            pass


class CommShardsBuffer(ShardsBuffer):
    """Batches outbound shards per destination worker and pushes them
    with a caller-provided async send (reference shuffle/_comms.py).

    ``message_bytes_limit`` (config ``shuffle.comm-message-bytes``) caps a
    single RPC message: a backed-up bucket is split into several sends
    rather than serialized as one giant message (reference _comms.py
    message-bytes-limit semantics)."""

    def __init__(
        self,
        send: Callable[[str, list], Awaitable[None]],
        limiter: ResourceLimiter | None = None,
        concurrency: int = 4,
        message_bytes_limit: int | None = None,
    ):
        super().__init__(limiter=limiter, concurrency=concurrency)
        self._send = send
        self.message_bytes_limit = message_bytes_limit

    async def _process(self, id: Any, shards: list) -> None:
        limit = self.message_bytes_limit
        if not limit or len(shards) <= 1:
            await self._send(id, shards)
            return
        batch: list = []
        batch_bytes = 0
        for shard in shards:
            n = _nbytes(shard)
            if batch and batch_bytes + n > limit:
                await self._send(id, batch)
                batch = []
                batch_bytes = 0
            batch.append(shard)
            batch_bytes += n
        if batch:
            await self._send(id, batch)
