"""Device-resident P2P shuffle: shard movement over mesh collectives.

The host engine (``shuffle.core``) moves every shard as msgpack frames
over TCP/inproc — the right plane for host objects, the WRONG one for
jax arrays already living on an accelerator mesh.  This module is the
TPU-native analogue of the reference's UCX data plane
(reference comm/ucx.py:211, frames carrying CUDA buffers :302-360):
partitions stay on their devices; the exchange is ONE XLA all-to-all
over the mesh interconnect (``ops.ici.shuffle_on_mesh``); the host RPC
layer carries only control (run specs, epoch fencing, the barrier).

Topology model: every participating worker owns one mesh device (the
virtual 8-CPU mesh in tests; one chip per worker process on real pods).
All workers live where the jax runtime can address the whole mesh — in
a multi-host deployment that is exactly the ``jax.distributed`` SPMD
model, where each host enters the same program with its local shards
and XLA runs the collective across hosts; in the in-process test
harness one execution covers every device and the results are shared
through the process-level store.

Flow (graph shapes mirror ``shuffle.api``):

    transfer(i): REGISTER partition i's device arrays in the store —
                 no splitting, no pushes, no serialization
    barrier:     scheduler-fenced; the first arriving body executes the
                 mesh exchange once per (id, run_id) epoch
    unpack(j):   slice output shard j from the exchanged global arrays
                 (device-resident; only the tiny counts vector touches
                 the host, as control data)

Epoch fencing rides the existing scheduler extension: a lost worker
bumps ``run_id``, releasing the pipeline; stale registrations are
dropped by (id, run_id) keying exactly like the host engine.
"""

from __future__ import annotations

import asyncio
import logging
import threading
import time as _time
from collections import deque
from typing import Any

import numpy as np

logger = logging.getLogger("distributed_tpu.shuffle")


class DeviceRun:
    """Per-(id, run_id) device-shard registry + one-shot exchange."""

    def __init__(self, id: str, run_id: int, n_inputs: int,
                 npartitions_out: int):
        self.id = id
        self.run_id = run_id
        self.n_inputs = n_inputs
        self.npartitions_out = npartitions_out
        self.parts: dict[int, tuple[Any, Any]] = {}
        self.outputs: dict[int, tuple[Any, Any]] | None = None
        self.local_ids: list[int] = []
        self.served: set[int] = set()
        self.last_activity = _time.monotonic()
        self.lock = threading.Lock()

    def touch(self) -> None:
        self.last_activity = _time.monotonic()

    def register(self, pid: int, keys: Any, values: Any) -> None:
        with self.lock:
            self.touch()
            self.parts[int(pid)] = (keys, values)

    # ----------------------------------------------------------- exchange

    def exchange(self, max_n: int | None = None) -> None:
        """Run the mesh all-to-all once; idempotent per epoch.

        SPMD-by-construction: this process contributes shards only for
        its LOCAL mesh devices (``self.parts`` — in a multi-host pod the
        transfer tasks are pinned to device owners, so each process's
        store holds exactly its own partitions) and slices outputs only
        for local devices.  On a single host "local" is all of them and
        this degenerates to the one-process exchange.  In a pod, every
        participating process must call this concurrently (the barrier
        fans out ``device_shuffle_exchange`` RPCs) so the jitted
        collective can rendezvous across hosts.

        ``max_n``: the GLOBAL max partition length (from the transfer
        results via the barrier); required in multi-host mode where no
        process sees every partition.  Ragged lengths are padded to it
        and masked out of the exchange (``valid``), so no padding row
        ever crosses the interconnect as data.
        """
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from distributed_tpu.ops.ici import make_mesh_1d, shuffle_on_mesh

        with self.lock:
            self.touch()
            if self.outputs is not None:
                return
            n_dev = self.n_inputs
            mesh = make_mesh_1d(n_dev)
            devices = list(mesh.devices.flat)
            local_ids = [
                d for d in range(n_dev)
                if devices[d].process_index == jax.process_index()
            ]
            if not local_ids and not self.parts:
                # this process owns none of the shuffle's mesh devices
                # AND holds no registrations (e.g. the barrier landed on
                # a non-participant): nothing to contribute, and the
                # SPMD program must not run here — the owners' exchanges
                # carry the epoch.  Keep outputs None: a stray unpack
                # here must take the Reschedule/restart path, not KeyError.
                self.local_ids = []
                return
            if set(self.parts) != set(local_ids):
                raise RuntimeError(
                    f"device shuffle {self.id} run {self.run_id}: "
                    f"registered partitions {sorted(self.parts)} != local "
                    f"mesh devices {local_ids}"
                )
            if max_n is None:
                # single-host callers: every length is visible here
                max_n = max(
                    (int(k.shape[0]) for k, _ in self.parts.values()),
                    default=1,
                )
            max_n = max(int(max_n), 1)
            val_shape = next(iter(self.parts.values()))[1].shape[1:]

            k_shards, v_shards, m_shards = [], [], []
            for d in local_ids:
                keys, values = self.parts[d]
                n = int(keys.shape[0])
                keys = jnp.asarray(keys, jnp.int32)
                pad = max_n - n
                if pad:
                    keys = jnp.concatenate(
                        [keys, jnp.zeros(pad, jnp.int32)]
                    )
                    values = jnp.concatenate(
                        [values,
                         jnp.zeros((pad, *val_shape), values.dtype)]
                    )
                mask = jnp.arange(max_n) < n
                # one-per-device placement: a partition produced on the
                # right device moves nothing; a misplaced one pays one
                # device-to-device copy, never a host serialization
                k_shards.append(jax.device_put(keys, devices[d]))
                v_shards.append(jax.device_put(values, devices[d]))
                m_shards.append(jax.device_put(mask, devices[d]))

            sharding = NamedSharding(mesh, P("shuffle"))
            # make_array_from_single_device_arrays needs only the
            # ADDRESSABLE shards — the other processes supply theirs
            K = jax.make_array_from_single_device_arrays(
                (n_dev * max_n,), sharding, k_shards
            )
            V = jax.make_array_from_single_device_arrays(
                (n_dev * max_n, *val_shape), sharding, v_shards
            )
            M = jax.make_array_from_single_device_arrays(
                (n_dev * max_n,), sharding, m_shards
            )
            # generous capacity: every row of one source could hash to
            # the same destination
            ko, vo, counts, _sent = shuffle_on_mesh(
                mesh, K, V, capacity=max_n, valid=M
            )
            # counts are control data: the ONLY bytes that touch the
            # host — read per-shard (never np.asarray the global array:
            # it is not fully addressable in a pod)
            cnt_by_dev: dict[int, Any] = {}
            for shard in counts.addressable_shards:
                d = shard.index[0].start // n_dev
                cnt_by_dev[d] = np.asarray(shard.data)
            k_by_dev = {
                devices.index(s.device): s.data
                for s in ko.addressable_shards
            }
            v_by_dev = {
                devices.index(s.device): s.data
                for s in vo.addressable_shards
            }

            outputs: dict[int, tuple[Any, Any]] = {}
            for d in local_ids:
                cnt = cnt_by_dev[d]
                if (cnt > max_n).any():  # pragma: no cover - cap==max_n
                    raise RuntimeError("device shuffle truncated a block")
                kshard = k_by_dev[d]  # [n_dev, max_n] rows for dest d
                vshard = v_by_dev[d]
                kparts = [kshard[s, : int(cnt[s])] for s in range(n_dev)]
                vparts = [vshard[s, : int(cnt[s])] for s in range(n_dev)]
                outputs[d] = (
                    jnp.concatenate(kparts) if kparts else kshard[:0],
                    jnp.concatenate(vparts) if vparts else vshard[:0],
                )
            self.outputs = outputs
            self.local_ids = list(local_ids)


class DeviceShuffleStore:
    """Process-level registry of device runs (one jax runtime)."""

    def __init__(self) -> None:
        self.runs: dict[tuple[str, int], DeviceRun] = {}
        # epochs fully served and collected: a straggling DUPLICATE task
        # execution (steal race, speculative rerun) must not resurrect
        # an empty run that would pin device memory forever
        self.done: "deque[tuple[str, int]]" = deque(maxlen=256)
        self._done_set: set[tuple[str, int]] = set()
        # newest epoch ever seen per shuffle id: a straggling registration
        # carrying an OLDER run_id (fetched just before a restart bump)
        # must not re-create a dead epoch and pin its input arrays.
        # Bounded (insertion-ordered eviction) — shuffle ids are fresh
        # uuids, so without a cap this grows for the process lifetime.
        self._max_run: dict[str, int] = {}
        self._max_run_cap = 4096
        # served epochs that already absorbed ONE duplicate-unpack
        # reschedule: a second miss means the output is genuinely gone
        # (not a steal-race duplicate) and must restart the epoch
        self._served_rescheduled: set[tuple[str, int, int]] = set()
        self.lock = threading.Lock()

    def get_or_create(self, id: str, run_id: int, n_inputs: int,
                      npartitions_out: int) -> DeviceRun | None:
        """The live run for this epoch, or None when the epoch already
        completed (duplicate execution of a finished task) or was
        superseded by a newer epoch (straggler with a stale run_id)."""
        with self.lock:
            if (id, run_id) in self._done_set:
                return None
            if run_id < self._max_run.get(id, -1):
                return None
            run = self.runs.get((id, run_id))
            if run is None:
                run = self.runs[(id, run_id)] = DeviceRun(
                    id, run_id, n_inputs, npartitions_out
                )
                self._max_run.pop(id, None)  # re-insert at newest position
                self._max_run[id] = run_id
                while len(self._max_run) > self._max_run_cap:
                    del self._max_run[next(iter(self._max_run))]
                # stale epochs of the same shuffle can be dropped
                for key in [k for k in self.runs if k[0] == id and k[1] < run_id]:
                    del self.runs[key]
            return run

    def was_served(self, id: str, run_id: int) -> bool:
        """True when this epoch finished and was collected (every local
        output unpacked into worker memory)."""
        with self.lock:
            return (id, run_id) in self._done_set

    def was_served_once(self, id: str, run_id: int, pid: int) -> bool:
        """True the FIRST time a finished-and-collected epoch sees a
        duplicate unpack of partition ``pid`` — the cheap reschedule
        path.  A second miss for the same partition means the unpacked
        output was genuinely lost afterwards (eviction, worker death
        without an epoch bump): the caller must restart the epoch, or a
        bare reschedule would livelock forever."""
        with self.lock:
            if (id, run_id) not in self._done_set:
                return False
            tag = (id, run_id, int(pid))
            if tag in self._served_rescheduled:
                return False
            self._served_rescheduled.add(tag)
            return True

    def forget(self, id: str, run_id: int | None = None,
               only_idle_for: float | None = None) -> None:
        """Collect device runs of ``id`` (all epochs, or only epochs
        <= ``run_id``).  Wired into the worker extension's run-TTL
        cleanup so abandoned epochs don't pin device arrays.

        ``only_idle_for``: skip runs touched more recently than this many
        seconds.  The TTL cleanup fires per-WORKER off one worker's host
        run going idle, but the device store is process-global: a
        transfer-only worker's 5s-idle cleanup must not collect an
        exchange other in-process workers are still unpacking.
        """
        now = _time.monotonic()
        with self.lock:
            for key in [
                k for k, r in self.runs.items()
                if k[0] == id and (run_id is None or k[1] <= run_id)
                and (only_idle_for is None
                     or now - r.last_activity >= only_idle_for)
            ]:
                del self.runs[key]

    def mark_served(self, run: DeviceRun, pid: int) -> None:
        """Drop the run once every output partition was unpacked — the
        results live in the worker data stores from then on, and keeping
        the run would pin all inputs AND outputs in device memory for
        the process lifetime.  A recomputed unpack (worker loss) arrives
        under a BUMPED run_id and re-exchanges from fresh registrations."""
        with self.lock:
            run.touch()
            run.served.add(int(pid))
            # inputs are dead weight as soon as the exchange ran
            run.parts.clear()
            # collect once every LOCAL output left for worker memory —
            # in a pod this process only ever serves its own devices
            n_local = len(run.local_ids) or run.npartitions_out
            if len(run.served) >= n_local:
                self.runs.pop((run.id, run.run_id), None)
                key = (run.id, run.run_id)
                if key not in self._done_set:
                    if len(self.done) == self.done.maxlen:
                        self._done_set.discard(self.done[0])
                    self.done.append(key)
                    self._done_set.add(key)


async def _run_in_daemon_thread(fn, *args):
    """Run a potentially-wedging call (a cross-host collective whose
    rendezvous may never complete) on a THROWAWAY daemon thread.  The
    shared default executor must not absorb the block: its threads also
    serve spill/compile work, and one leaked thread per wedged epoch
    starves the worker.  A daemon thread leaks nothing the process
    cares about and dies with it."""
    loop = asyncio.get_running_loop()
    done = asyncio.Event()
    box: list = []

    def run():
        try:
            box.append((True, fn(*args)))
        except BaseException as exc:  # noqa: BLE001 - relayed to awaiter
            box.append((False, exc))
        try:
            loop.call_soon_threadsafe(done.set)
        except RuntimeError:
            pass

    threading.Thread(target=run, daemon=True,
                     name="dtpu-device-exchange").start()
    await done.wait()
    ok, val = box[0]
    if not ok:
        raise val
    return val


_store: DeviceShuffleStore | None = None


def device_store() -> DeviceShuffleStore:
    global _store
    if _store is None:
        _store = DeviceShuffleStore()
    return _store


# ------------------------------------------------------------ task bodies


async def _spec_for(shuffle_id: str):
    from distributed_tpu.worker.context import get_worker

    worker = get_worker()
    run = await worker.shuffle.get_or_create_remote(shuffle_id)
    return worker, run


async def device_shuffle_transfer(data: Any, shuffle_id: str,
                                  partition_id: int) -> tuple[int, int]:
    """Register one device partition; zero data movement.  Returns
    ``(partition_id, n_rows)`` — the barrier needs the GLOBAL max
    length to size the exchange when no process sees every partition."""
    worker, run = await _spec_for(shuffle_id)
    keys, values = data
    store_run = device_store().get_or_create(
        shuffle_id, run.run_id, run.spec.n_inputs,
        run.spec.npartitions_out,
    )
    if store_run is not None:  # None: duplicate rerun of a finished epoch
        store_run.register(partition_id, keys, values)
    return int(partition_id), int(keys.shape[0])


async def device_shuffle_exchange_handler(worker: Any, id: str = "",
                                          run_id: int = 0,
                                          max_n: int = 0) -> dict:
    """Worker RPC: enter this epoch's mesh exchange with OUR local
    shards.  In a pod every participant must be inside the jitted
    collective together — the barrier fans this out concurrently and
    the per-process executions rendezvous in XLA."""
    run = await worker.shuffle.get_or_create_remote(id)
    if run.run_id != run_id:
        return {"status": "stale", "run_id": run.run_id}
    store_run = device_store().get_or_create(
        id, run_id, run.spec.n_inputs, run.spec.npartitions_out,
    )
    if store_run is None:
        return {"status": "done"}
    await _run_in_daemon_thread(store_run.exchange, max_n)
    return {"status": "OK"}


async def device_shuffle_precheck_handler(worker: Any, id: str = "",
                                          run_id: int = 0) -> dict:
    """Worker RPC: confirm this process is on the SAME epoch with all
    of its local partitions registered, WITHOUT entering the collective.
    The barrier runs this all-or-nothing round first — one participant
    skipping the exchange (stale epoch) while the others are already
    blocked inside the cross-host collective would wedge them forever."""
    run = await worker.shuffle.get_or_create_remote(id)
    if run.run_id != run_id:
        return {"status": "stale", "run_id": run.run_id}
    if device_store().was_served(id, run_id):
        # duplicate rerun of a FINISHED epoch (steal race): outputs are
        # already in worker memory — the barrier must no-op, not restart
        return {"status": "done"}
    store_run = device_store().runs.get((id, run_id))
    if store_run is None:
        return {"status": "no-parts"}
    return {"status": "OK", "n_parts": len(store_run.parts)}


async def device_shuffle_barrier(shuffle_id: str,
                                 *transfer_results) -> int:
    """Scheduler-fenced barrier, then the mesh exchange.

    Single-host: one exchange call covers all devices.  Multi-host
    pod (``spec.device_owned``): precheck every participant is on this
    epoch, then fan the exchange out so each process joins the
    collective with its local shards."""
    worker, run = await _spec_for(shuffle_id)
    await run.barrier()
    max_n = max((int(n) for _, n in transfer_results), default=1)
    participants = set(run.spec.worker_for.values())
    if _multihost() and not run.spec.device_owned and len(participants) > 1:
        # overlapping/non-covering device ownership (e.g. several
        # worker processes sharing one jax runtime): registrations
        # are scattered across processes and no SPMD exchange can
        # assemble them.  Fail loudly with the remedy.
        raise RuntimeError(
            "device shuffle on a multi-process pod requires "
            "device-owned placement: start ONE worker process per "
            "chip group with --jax-coordinator/--jax-process-id so "
            "ownership is disjoint (got round-robin worker_for)"
        )
    if _multihost() and run.spec.device_owned:
        # fan out — even to a single owner: this barrier task may be
        # running on a NON-owner process with no shards
        timeout = 120.0

        async def call(addr: str, op: str):
            if addr == worker.address:
                fn = (device_shuffle_exchange_handler if op == "exchange"
                      else device_shuffle_precheck_handler)
                kwargs = {"id": shuffle_id, "run_id": run.run_id}
                if op == "exchange":
                    kwargs["max_n"] = max_n
                return await fn(worker, **kwargs)
            peer = worker.rpc(addr)
            if op == "exchange":
                return await peer.device_shuffle_exchange(
                    id=shuffle_id, run_id=run.run_id, max_n=max_n
                )
            return await peer.device_shuffle_precheck(
                id=shuffle_id, run_id=run.run_id
            )

        addrs = sorted(participants)
        pre = await asyncio.wait_for(
            asyncio.gather(*(call(a, "precheck") for a in addrs)), timeout
        )
        if any(r.get("status") == "done" for r in pre):
            # the epoch already completed globally (duplicate barrier
            # rerun): outputs live in worker memory; nothing to exchange
            return run.run_id
        bad = [
            (a, r) for a, r in zip(addrs, pre) if r.get("status") != "OK"
        ]
        if bad:
            raise RuntimeError(f"device exchange precheck failed: {bad!r}")
        # every process now enters the collective together; the timeout
        # turns a wedged rendezvous (participant died between rounds)
        # into a barrier error -> epoch restart instead of a hang
        results = await asyncio.wait_for(
            asyncio.gather(*(call(a, "exchange") for a in addrs)), timeout
        )
        bad = [r for r in results if r.get("status") not in ("OK", "done")]
        if bad:
            raise RuntimeError(f"device exchange failed: {bad!r}")
        return run.run_id
    store_run = device_store().get_or_create(
        shuffle_id, run.run_id, run.spec.n_inputs,
        run.spec.npartitions_out,
    )
    if store_run is not None:  # None: duplicate rerun of a finished epoch
        # the collective is a compile+execute: keep the event loop free
        await _run_in_daemon_thread(store_run.exchange, max_n)
    return run.run_id


def _multihost() -> bool:
    from distributed_tpu.parallel.multihost import is_multihost

    return is_multihost()


async def device_shuffle_unpack(shuffle_id: str, partition_id: int,
                                barrier_result: int) -> Any:
    """Output partition j as device-resident (keys, values)."""
    from distributed_tpu.exceptions import Reschedule

    worker, run = await _spec_for(shuffle_id)
    store_run = device_store().runs.get((shuffle_id, run.run_id))
    if store_run is None or store_run.outputs is None:
        if device_store().was_served_once(shuffle_id, run.run_id,
                                          partition_id):
            # duplicate execution of a FINISHED epoch (steal race,
            # speculative rerun): every output already sits in worker
            # memory — rescheduling is enough; a shuffle_restart RPC
            # here would re-run the whole completed shuffle.  Once only:
            # a SECOND miss for this partition means the output really
            # vanished and the restart path below must run.
            raise Reschedule(
                f"shuffle {shuffle_id} run {run.run_id} already served"
            )
        # epoch raced past us (restart, or the run was already
        # collected): ask for a fresh epoch and reschedule, like the
        # host-engine bodies (shuffle/api.py _restart_and_reschedule)
        from distributed_tpu.shuffle.api import _restart_and_reschedule

        await _restart_and_reschedule(worker, shuffle_id, run.run_id)
    out = store_run.outputs[int(partition_id)]
    device_store().mark_served(store_run, partition_id)
    return out


# --------------------------------------------------------- graph builder


async def p2p_shuffle_device(client: Any, inputs: list) -> list:
    """Hash-shuffle device-resident (keys, values) partitions over the
    mesh interconnect; returns futures of device-resident outputs.

    ``inputs``: one future per mesh device, each resolving to
    ``(keys i32[N_i], values [N_i, ...])`` jax arrays.  Output partition
    d holds every row with ``murmur3(key) % n_devices == d``, resident
    on mesh device d.
    """
    import uuid

    from distributed_tpu.graph.spec import Graph, TaskRef, TaskSpec
    from distributed_tpu.shuffle.api import _create_shuffle

    n = len(inputs)
    shuffle_id = f"devshuffle-{uuid.uuid4().hex[:12]}"
    worker_for, device_owned = await _create_shuffle(
        client, shuffle_id, n, n, device=True
    )

    g = Graph()
    transfer_keys = []
    annotations: dict = {}
    for i, fut in enumerate(inputs):
        k = f"{shuffle_id}-transfer-{i}"
        g.tasks[k] = TaskSpec(
            device_shuffle_transfer, (TaskRef(fut.key), shuffle_id, i)
        )
        if device_owned:
            # multi-host pod: partition i must REGISTER in the process
            # owning global mesh device i — a transfer elsewhere would
            # have to move the shard off its chips
            annotations[k] = {"workers": [worker_for[i]]}
        transfer_keys.append(k)
    barrier_key = f"{shuffle_id}-barrier"
    g.tasks[barrier_key] = TaskSpec(
        device_shuffle_barrier,
        (shuffle_id, *[TaskRef(k) for k in transfer_keys]),
    )
    unpack_keys = []
    for j in range(n):
        k = f"{shuffle_id}-unpack-{j}"
        g.tasks[k] = TaskSpec(
            device_shuffle_unpack, (shuffle_id, j, TaskRef(barrier_key))
        )
        unpack_keys.append(k)
        annotations[k] = {"workers": [worker_for[j]]}
    futs = client._graph_to_futures(
        dict(g.tasks), unpack_keys, annotations_by_key=annotations,
    )
    return [futs[k] for k in unpack_keys]
