"""Columnar (struct-of-arrays) shuffle path.

The reference hash-partitions arrow tables (shuffle/_arrow.py,
_shuffle.py:617: ``split_by_worker`` on a pyarrow Table).  The TPU-native
equivalent keeps partitions as dicts of numpy arrays — the layout jax
consumes zero-copy — and hash-splits them with vectorized numpy (one
argsort per input partition instead of a python loop per row, ~100x the
record-list path).

A partition is ``{column_name: np.ndarray}``; all columns share length.
"""

from __future__ import annotations

from typing import Any, Callable

import numpy as np


def _splitmix64(x: np.ndarray) -> np.ndarray:
    """Vectorized splitmix64 finalizer: deterministic across processes
    (builtin hash() is salted per interpreter)."""
    z = x.astype(np.uint64, copy=True)
    z += np.uint64(0x9E3779B97F4A7C15)
    z ^= z >> np.uint64(30)
    z *= np.uint64(0xBF58476D1CE4E5B9)
    z ^= z >> np.uint64(27)
    z *= np.uint64(0x94D049BB133111EB)
    z ^= z >> np.uint64(31)
    return z


def hash_column(col: np.ndarray) -> np.ndarray:
    """u64 hash per row; integer/float columns vectorize, strings hash
    via the (slow) python path."""
    if col.dtype.kind in "iub":
        return _splitmix64(col)
    if col.dtype.kind == "f":
        # +0.0 canonicalizes -0.0 (equal keys must share a partition)
        c = (col + 0.0) if col.dtype.itemsize == 8 else (
            col.astype(np.float64) + 0.0
        )
        return _splitmix64(c.view(np.uint64))
    from distributed_tpu.shuffle.core import stable_hash

    return np.fromiter(
        (stable_hash(x) & 0xFFFFFFFFFFFFFFFF for x in col.tolist()),
        np.uint64, count=len(col),
    )


def validate_partition(data: dict[str, np.ndarray]) -> int:
    if not isinstance(data, dict) or not data:
        raise TypeError(
            "columnar partition must be a non-empty {column: ndarray} dict"
        )
    n = None
    for c, v in data.items():
        if not isinstance(v, np.ndarray):
            raise TypeError(f"column {c!r} is not an ndarray: {type(v)}")
        if n is None:
            n = len(v)
        elif len(v) != n:
            raise ValueError(f"column {c!r} length {len(v)} != {n}")
    return n or 0


def split_arrays_by_hash(
    data: dict[str, np.ndarray], npartitions: int, on: str
) -> dict[int, dict[str, np.ndarray]]:
    """Hash-split one columnar partition into output partitions: a single
    stable argsort groups rows, then every column is sliced with one
    fancy-index per output (reference _shuffle.py:617 split_by_worker)."""
    validate_partition(data)
    keys = data[on]
    idx = (hash_column(keys) % np.uint64(npartitions)).astype(np.int64)
    order = np.argsort(idx, kind="stable")
    sorted_idx = idx[order]
    bounds = np.searchsorted(sorted_idx, np.arange(npartitions + 1))
    out: dict[int, dict[str, np.ndarray]] = {}
    for j in range(npartitions):
        lo, hi = int(bounds[j]), int(bounds[j + 1])
        if lo == hi:
            continue
        rows = order[lo:hi]
        out[j] = {c: np.ascontiguousarray(v[rows]) for c, v in data.items()}
    return out


def make_columnar_splitter(on: str) -> Callable:
    def splitter(data: Any, npartitions: int) -> dict[int, Any]:
        return split_arrays_by_hash(data, npartitions, on)

    return splitter


def concat_arrays(shards: list) -> dict[str, np.ndarray]:
    """Assemble an output partition from columnar shards."""
    if not shards:
        return {}
    cols = list(shards[0])
    return {
        c: np.concatenate([s[c] for s in shards]) if len(shards) > 1
        else shards[0][c]
        for c in cols
    }


def _empty_like_row(col: np.ndarray, n: int) -> np.ndarray:
    """n filler rows for outer-join misses: NaN for floats, minimum for
    ints (callers wanting NULL semantics should use float columns)."""
    if col.dtype.kind == "f":
        return np.full(n, np.nan, col.dtype)
    return np.zeros(n, col.dtype)


def join_arrays(
    left: dict[str, np.ndarray],
    right: dict[str, np.ndarray],
    on: str,
    how: str = "inner",
    rsuffix: str = "_right",
) -> dict[str, np.ndarray]:
    """Vectorized hash/sort-merge join of two co-partitioned columnar
    partitions (the columnar analogue of reference shuffle/_merge.py:434).

    Duplicate keys produce the full cross product per key, like a SQL
    join.  Right-side columns colliding with left names get ``rsuffix``.
    """
    if how not in ("inner", "left", "right", "outer"):
        raise ValueError(how)
    # a hash bucket may be empty on one side ({} from an unpopulated
    # output partition): treat it as zero rows of the other side's schema
    if not left or not right:
        other = right if not left else left
    if not left:
        left = {on: np.empty(0, other[on].dtype if other else np.int64)}
    if not right:
        right = {on: np.empty(0, other[on].dtype if other else np.int64)}
    lk = left[on]
    rk = right[on]
    rs = np.argsort(rk, kind="stable")
    rks = rk[rs]
    starts = np.searchsorted(rks, lk, "left")
    ends = np.searchsorted(rks, lk, "right")
    counts = ends - starts
    total = int(counts.sum())
    li = np.repeat(np.arange(len(lk)), counts)
    offs = np.zeros(len(counts), np.int64)
    if len(counts) > 1:
        offs[1:] = np.cumsum(counts[:-1])
    ri_flat = (
        np.arange(total, dtype=np.int64)
        - np.repeat(offs, counts)
        + np.repeat(starts, counts)
    )
    ri = rs[ri_flat]

    def rname(c: str) -> str:
        return c if c == on else (c + rsuffix if c in left else c)

    out = {c: v[li] for c, v in left.items()}
    for c, v in right.items():
        if c == on:
            continue
        out[rname(c)] = v[ri]

    if how in ("left", "outer"):
        miss_l = np.nonzero(counts == 0)[0]
        if len(miss_l):
            for c, v in left.items():
                out[c] = np.concatenate([out[c], v[miss_l]])
            for c, v in right.items():
                if c == on:
                    continue
                out[rname(c)] = np.concatenate(
                    [out[rname(c)], _empty_like_row(v, len(miss_l))]
                )
    if how in ("right", "outer"):
        # unmatched RIGHT rows, with left-column filler — implemented
        # natively so column naming stays identical across join types
        # (left columns bare, right columns suffixed)
        matched_r = np.zeros(len(rk), bool)
        matched_r[ri] = True
        miss_r = np.nonzero(~matched_r)[0]
        if len(miss_r):
            for c, v in left.items():
                if c == on:
                    out[c] = np.concatenate([out[c], rk[miss_r]])
                else:
                    out[c] = np.concatenate(
                        [out[c], _empty_like_row(v, len(miss_r))]
                    )
            for c, v in right.items():
                if c == on:
                    continue
                out[rname(c)] = np.concatenate([out[rname(c)], v[miss_r]])
    return out
