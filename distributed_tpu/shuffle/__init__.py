from distributed_tpu.shuffle.api import (
    p2p_merge,
    p2p_merge_arrays,
    p2p_rechunk,
    p2p_shuffle,
    p2p_shuffle_arrays,
)
from distributed_tpu.shuffle.buffers import (
    CommShardsBuffer,
    DiskShardsBuffer,
    MemoryShardsBuffer,
    ResourceLimiter,
)
from distributed_tpu.shuffle.core import (
    ShuffleRun,
    ShuffleSpec,
    ShuffleWorkerExtension,
)
from distributed_tpu.shuffle.device import (
    DeviceShuffleStore,
    device_store,
    p2p_shuffle_device,
)
from distributed_tpu.shuffle.scheduler_ext import ShuffleSchedulerExtension

__all__ = [
    "p2p_shuffle",
    "p2p_shuffle_arrays",
    "p2p_shuffle_device",
    "DeviceShuffleStore",
    "device_store",
    "p2p_rechunk",
    "p2p_merge",
    "p2p_merge_arrays",
    "ShuffleRun",
    "ShuffleSpec",
    "ShuffleWorkerExtension",
    "ShuffleSchedulerExtension",
    "ResourceLimiter",
    "MemoryShardsBuffer",
    "DiskShardsBuffer",
    "CommShardsBuffer",
]
