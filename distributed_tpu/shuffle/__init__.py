from distributed_tpu.shuffle.api import p2p_rechunk, p2p_shuffle
from distributed_tpu.shuffle.core import (
    ShuffleRun,
    ShuffleSpec,
    ShuffleWorkerExtension,
)

__all__ = [
    "p2p_shuffle",
    "p2p_rechunk",
    "ShuffleRun",
    "ShuffleSpec",
    "ShuffleWorkerExtension",
]
