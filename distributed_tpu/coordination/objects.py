"""Client-side coordination objects (reference semaphore.py:250, lock.py:75,
event.py:152, multi_lock.py:138, queues.py:128, variable.py:127,
pubsub.py:201,357).

Each object is a thin async proxy over the scheduler-hosted extension.
They accept either a ``Client`` or anything with a ``scheduler`` rpc
attribute (e.g. a ``Worker``), so tasks running on workers can use them
too.
"""

from __future__ import annotations

import asyncio
import logging
import uuid
from typing import Any

from distributed_tpu.rpc.core import rpc as _rpc

logger = logging.getLogger("distributed_tpu.coordination")


def _scheduler_rpc(obj: Any):
    """Resolve an rpc to the scheduler from a Client/Worker/address."""
    if obj is None:
        raise ValueError("pass a Client (or Worker) to coordination objects")
    if isinstance(obj, str):
        return _rpc(obj)
    sched = getattr(obj, "scheduler", None)
    if sched is not None:
        return sched
    # Worker: rpc pool + known scheduler address
    if hasattr(obj, "scheduler_addr"):
        return obj.rpc(obj.scheduler_addr)
    raise TypeError(f"cannot find a scheduler rpc on {obj!r}")


class Event:
    """Cluster-wide event (reference event.py:152)."""

    def __init__(self, name: str | None = None, client: Any = None):
        self.name = name or f"event-{uuid.uuid4().hex[:12]}"
        self.scheduler = _scheduler_rpc(client)

    async def wait(self, timeout: float | None = None) -> bool:
        return await self.scheduler.event_wait(name=self.name, timeout=timeout)

    async def set(self) -> None:
        await self.scheduler.event_set(name=self.name)

    async def clear(self) -> None:
        await self.scheduler.event_clear(name=self.name)

    async def is_set(self) -> bool:
        return await self.scheduler.event_is_set(name=self.name)

    def __repr__(self) -> str:
        return f"<Event: {self.name!r}>"


class Lock:
    """Cluster-wide mutex (reference lock.py:75)."""

    def __init__(self, name: str | None = None, client: Any = None):
        self.name = name or f"lock-{uuid.uuid4().hex[:12]}"
        self.id = uuid.uuid4().hex
        self.scheduler = _scheduler_rpc(client)
        self._locked = False

    async def acquire(self, timeout: float | None = None) -> bool:
        ok = await self.scheduler.lock_acquire(
            name=self.name, id=self.id, timeout=timeout
        )
        if ok:
            self._locked = True
        return ok

    async def release(self) -> None:
        await self.scheduler.lock_release(name=self.name, id=self.id)
        self._locked = False

    async def locked(self) -> bool:
        return await self.scheduler.lock_locked(name=self.name)

    async def __aenter__(self) -> "Lock":
        await self.acquire()
        return self

    async def __aexit__(self, *exc: Any) -> None:
        await self.release()

    def __repr__(self) -> str:
        return f"<Lock: {self.name!r}>"


class MultiLock:
    """Acquire several named locks atomically (reference multi_lock.py:138)."""

    def __init__(self, names: list[str] = (), client: Any = None):
        self.names = list(names)
        self.id = uuid.uuid4().hex
        self.scheduler = _scheduler_rpc(client)

    async def acquire(self, timeout: float | None = None,
                      num_locks: int | None = None) -> bool:
        return await self.scheduler.multi_lock_acquire(
            locks=self.names, id=self.id, timeout=timeout, num_locks=num_locks
        )

    async def release(self) -> None:
        await self.scheduler.multi_lock_release(id=self.id)

    async def __aenter__(self) -> "MultiLock":
        await self.acquire()
        return self

    async def __aexit__(self, *exc: Any) -> None:
        await self.release()


class Semaphore:
    """Counting semaphore with auto-refreshing leases
    (reference semaphore.py:250)."""

    def __init__(self, max_leases: int = 1, name: str | None = None,
                 client: Any = None):
        self.name = name or f"semaphore-{uuid.uuid4().hex[:12]}"
        self.max_leases = max_leases
        self.scheduler = _scheduler_rpc(client)
        self._leases: list[str] = []
        self._registered: asyncio.Future | None = None
        self._refresh_task: asyncio.Task | None = None

    async def _register(self) -> None:
        await self.scheduler.semaphore_register(
            name=self.name, max_leases=self.max_leases
        )

    def _ensure_refresh(self) -> None:
        if self._refresh_task is None or self._refresh_task.done():
            self._refresh_task = asyncio.create_task(self._refresh_loop())

    async def _refresh_loop(self) -> None:
        while self._leases:
            try:
                await self.scheduler.semaphore_refresh_leases(
                    name=self.name, lease_ids=list(self._leases)
                )
            except asyncio.CancelledError:
                raise
            except Exception:
                # transient comm failure: keep trying — a dead refresh loop
                # would let the scheduler expire a still-held lease
                logger.warning(
                    "semaphore %r lease refresh failed; retrying", self.name
                )
            await asyncio.sleep(5)

    async def acquire(self, timeout: float | None = None) -> bool:
        await self._register()
        lease_id = uuid.uuid4().hex
        ok = await self.scheduler.semaphore_acquire(
            name=self.name, timeout=timeout, lease_id=lease_id
        )
        if ok:
            self._leases.append(lease_id)
            self._ensure_refresh()
        return ok

    async def release(self) -> bool:
        if not self._leases:
            raise ValueError("released too often")
        lease_id = self._leases.pop(0)
        return await self.scheduler.semaphore_release(
            name=self.name, lease_id=lease_id
        )

    async def get_value(self) -> int:
        return await self.scheduler.semaphore_value(name=self.name)

    async def close(self) -> None:
        if self._refresh_task is not None:
            self._refresh_task.cancel()
        await self.scheduler.semaphore_close(name=self.name)

    async def __aenter__(self) -> "Semaphore":
        await self.acquire()
        return self

    async def __aexit__(self, *exc: Any) -> None:
        await self.release()


class Queue:
    """Cluster-wide FIFO queue carrying data or Futures
    (reference queues.py:128)."""

    def __init__(self, name: str | None = None, client: Any = None,
                 maxsize: int = 0):
        self.name = name or f"queue-{uuid.uuid4().hex[:12]}"
        self.client = client
        self.scheduler = _scheduler_rpc(client)
        self.maxsize = maxsize
        self._created = False

    async def _create(self) -> None:
        if not self._created:
            await self.scheduler.queue_create(
                name=self.name, maxsize=self.maxsize
            )
            self._created = True

    async def put(self, value: Any = None, timeout: float | None = None) -> None:
        from distributed_tpu.client.client import Future
        from distributed_tpu.protocol.serialize import Serialize

        await self._create()
        if isinstance(value, Future):
            await self.scheduler.queue_put(
                name=self.name, key=value.key, timeout=timeout
            )
        else:
            await self.scheduler.queue_put(
                name=self.name, value=Serialize(value), timeout=timeout
            )

    async def get(self, timeout: float | None = None) -> Any:
        from distributed_tpu.protocol.serialize import unwrap

        await self._create()
        record = await self.scheduler.queue_get(name=self.name, timeout=timeout)
        return self._unpack(record, unwrap)

    def _unpack(self, record: dict, unwrap: Any) -> Any:
        if record["type"] == "Future":
            from distributed_tpu.client.client import Client, Future

            key = record["value"]
            if isinstance(self.client, Client):
                self.client._ensure_tracked(key)
                return Future(key, self.client)
            return key
        return unwrap(record["value"])

    async def qsize(self) -> int:
        await self._create()
        return await self.scheduler.queue_qsize(name=self.name)

    async def close(self) -> None:
        await self.scheduler.queue_release(name=self.name)


class Variable:
    """Cluster-wide mutable cell (reference variable.py:127)."""

    def __init__(self, name: str | None = None, client: Any = None):
        self.name = name or f"variable-{uuid.uuid4().hex[:12]}"
        self.client = client
        self.scheduler = _scheduler_rpc(client)

    async def set(self, value: Any) -> None:
        from distributed_tpu.client.client import Future
        from distributed_tpu.protocol.serialize import Serialize

        if isinstance(value, Future):
            await self.scheduler.variable_set(name=self.name, key=value.key)
        else:
            await self.scheduler.variable_set(
                name=self.name, value=Serialize(value)
            )

    async def get(self, timeout: float | None = None) -> Any:
        from distributed_tpu.protocol.serialize import unwrap

        record = await self.scheduler.variable_get(
            name=self.name, timeout=timeout
        )
        if record["type"] == "Future":
            from distributed_tpu.client.client import Client, Future

            key = record["value"]
            if isinstance(self.client, Client):
                self.client._ensure_tracked(key)
                return Future(key, self.client)
            return key
        return unwrap(record["value"])

    async def delete(self) -> None:
        await self.scheduler.variable_delete(name=self.name)


class Pub:
    """Publish to a topic (reference pubsub.py:201).  Client-side publishers
    relay through the scheduler stream."""

    def __init__(self, name: str, client: Any = None):
        self.name = name
        self.client = client

    def put(self, msg: Any) -> None:
        from distributed_tpu.client.client import Client

        if isinstance(self.client, Client):
            self.client.batched_stream.send(
                {"op": "pubsub-msg", "name": self.name, "msg": msg,
                 "client": self.client.id}
            )
        else:  # worker-side publisher
            self.client.batched_stream.send(
                {"op": "pubsub-msg", "name": self.name, "msg": msg}
            )


class Sub:
    """Subscribe to a topic (reference pubsub.py:357)."""

    def __init__(self, name: str, client: Any = None):
        self.name = name
        self.client = client
        self.buffer: asyncio.Queue = asyncio.Queue()
        from distributed_tpu.client.client import Client

        if isinstance(client, Client):
            client._pubsub_subs.setdefault(name, []).append(self)
            client.batched_stream.send(
                {"op": "pubsub-add-subscriber", "name": name,
                 "client": client.id}
            )
        else:  # worker-side
            client._pubsub_subs.setdefault(name, []).append(self)
            client.batched_stream.send(
                {"op": "pubsub-add-subscriber", "name": name}
            )

    def _put(self, msg: Any) -> None:
        self.buffer.put_nowait(msg)

    async def get(self, timeout: float | None = None) -> Any:
        return await asyncio.wait_for(self.buffer.get(), timeout)

    def __aiter__(self) -> "Sub":
        return self

    async def __anext__(self) -> Any:
        return await self.get()
