"""Scheduler-hosted coordination extensions.

Equivalents of the reference's cluster-wide primitives, all state held on
the scheduler and accessed over RPC:

- ``EventExtension``    (reference event.py:17)    — named async events
- ``LockExtension``     (reference lock.py:16)     — named mutexes
- ``MultiLockExtension``(reference multi_lock.py:18) — atomic multi-name locks
- ``SemaphoreExtension``(reference semaphore.py:22) — counting semaphores
  with lease timeouts: a crashed client's leases expire and free the slot
- ``QueueExtension``    (reference queues.py:17)   — named FIFO queues
- ``VariableExtension`` (reference variable.py:21) — named mutable cells
- ``PublishExtension``  (reference publish.py:10)  — named datasets kept
  alive by a synthetic client
- ``PubSubSchedulerExtension`` (reference pubsub.py:19) — topic fan-out

Payloads may be plain data or future keys; queues/variables track the keys
they hold via a per-extension synthetic client so the scheduler keeps the
results alive (reference queues.py:101, variable.py:60).
"""

from __future__ import annotations

import asyncio
import logging
import uuid
from collections import defaultdict, deque
from typing import TYPE_CHECKING, Any

from distributed_tpu.utils.misc import seq_name, time

if TYPE_CHECKING:
    from distributed_tpu.scheduler.server import Scheduler

logger = logging.getLogger("distributed_tpu.coordination")


class EventExtension:
    """Named events (reference event.py:17)."""

    def __init__(self, scheduler: "Scheduler"):
        self.scheduler = scheduler
        self._events: defaultdict[str, asyncio.Event] = defaultdict(asyncio.Event)
        self._waiters: defaultdict[str, int] = defaultdict(int)
        scheduler.handlers.update(
            {
                "event_wait": self.event_wait,
                "event_set": self.event_set,
                "event_clear": self.event_clear,
                "event_is_set": self.event_is_set,
            }
        )

    async def event_wait(self, name: str = "", timeout: float | None = None) -> bool:
        event = self._events[name]
        self._waiters[name] += 1
        try:
            await asyncio.wait_for(event.wait(), timeout)
            return True
        except asyncio.TimeoutError:
            return False
        finally:
            self._waiters[name] -= 1
            self._maybe_forget(name)

    async def event_set(self, name: str = "") -> None:
        self._events[name].set()

    async def event_clear(self, name: str = "") -> None:
        self._events[name].clear()
        self._maybe_forget(name)

    async def event_is_set(self, name: str = "") -> bool:
        return self._events[name].is_set()

    def _maybe_forget(self, name: str) -> None:
        ev = self._events.get(name)
        if ev is not None and not ev.is_set() and not self._waiters[name]:
            self._events.pop(name, None)
            self._waiters.pop(name, None)


class LockExtension:
    """Named mutexes with reentrancy tokens (reference lock.py:16)."""

    def __init__(self, scheduler: "Scheduler"):
        self.scheduler = scheduler
        self.ids: dict[str, str] = {}  # name -> owner id
        self.events: defaultdict[str, asyncio.Event] = defaultdict(asyncio.Event)
        self._waiters: defaultdict[str, int] = defaultdict(int)
        scheduler.handlers.update(
            {
                "lock_acquire": self.acquire,
                "lock_release": self.release,
                "lock_locked": self.locked,
            }
        )

    async def acquire(self, name: str = "", id: str = "",
                      timeout: float | None = None) -> bool:
        deadline = None if timeout is None else time() + timeout
        while name in self.ids:
            if self.ids.get(name) == id:
                return True  # reentrant
            event = self.events[name]
            remaining = None if deadline is None else deadline - time()
            if remaining is not None and remaining <= 0:
                self._maybe_forget(name)
                return False
            self._waiters[name] += 1
            try:
                await asyncio.wait_for(event.wait(), remaining)
            except asyncio.TimeoutError:
                return False
            finally:
                self._waiters[name] -= 1
        self.ids[name] = id
        self.events[name].clear()
        return True

    async def release(self, name: str = "", id: str = "") -> bool:
        if self.ids.get(name) != id:
            raise ValueError(f"lock {name!r} not held by {id!r}")
        del self.ids[name]
        self.events[name].set()
        # fresh event for the next holder cycle
        self.events[name] = asyncio.Event()
        self._maybe_forget(name)
        return True

    def _maybe_forget(self, name: str) -> None:
        """Drop bookkeeping for free, unwaited locks (uuid-named locks
        would otherwise accumulate without bound)."""
        if name not in self.ids and not self._waiters.get(name):
            self.events.pop(name, None)
            self._waiters.pop(name, None)

    async def locked(self, name: str = "") -> bool:
        return name in self.ids


class MultiLockExtension:
    """Atomically acquire several named locks (reference multi_lock.py:18)."""

    def __init__(self, scheduler: "Scheduler"):
        self.scheduler = scheduler
        self.locks: defaultdict[str, list[str]] = defaultdict(list)  # name -> waiter queue
        self.requests: dict[str, set[str]] = {}  # id -> names wanted
        self.requests_left: dict[str, int] = {}  # id -> locks still needed
        self.events: dict[str, asyncio.Event] = {}
        scheduler.handlers.update(
            {
                "multi_lock_acquire": self.acquire,
                "multi_lock_release": self.release,
            }
        )

    async def acquire(self, locks: list[str] = (), id: str = "",
                      timeout: float | None = None, num_locks: int | None = None
                      ) -> bool:
        locks = list(locks)
        num_locks = num_locks if num_locks is not None else len(locks)
        self.requests[id] = set(locks)
        self.events[id] = asyncio.Event()
        acquired_now = 0
        for name in locks:
            queue = self.locks[name]
            queue.append(id)
            if queue[0] == id:
                acquired_now += 1
        self.requests_left[id] = num_locks - acquired_now
        if self.requests_left[id] <= 0:
            self._trim_request(id, locks, num_locks)
            return True
        try:
            await asyncio.wait_for(self.events[id].wait(), timeout)
            self._trim_request(id, locks, num_locks)
            return True
        except asyncio.TimeoutError:
            await self.release(id=id)
            return False
        finally:
            self.events.pop(id, None)

    def _trim_request(self, id: str, locks: list[str], num_locks: int) -> None:
        """Keep only the first num_locks acquired names for this request."""
        if num_locks >= len(locks):
            return
        held = [n for n in locks if self.locks[n] and self.locks[n][0] == id]
        for name in held[num_locks:]:
            self._release_one(name, id)
        self.requests[id] = set(held[:num_locks])

    def _release_one(self, name: str, id: str) -> None:
        queue = self.locks.get(name)
        if not queue or id not in queue:
            return
        was_head = queue[0] == id
        queue.remove(id)
        if not queue:
            del self.locks[name]
            return
        if was_head:
            new_head = queue[0]
            if new_head in self.requests_left:
                self.requests_left[new_head] -= 1
                if self.requests_left[new_head] <= 0:
                    ev = self.events.get(new_head)
                    if ev is not None:
                        ev.set()

    async def release(self, id: str = "") -> None:
        names = self.requests.pop(id, set())
        self.requests_left.pop(id, None)
        for name in list(names):
            self._release_one(name, id)


class SemaphoreExtension:
    """Counting semaphores with expiring leases (reference semaphore.py:22)."""

    LEASE_TIMEOUT = 30.0

    def __init__(self, scheduler: "Scheduler"):
        self.scheduler = scheduler
        self.max_leases: dict[str, int] = {}
        # name -> {lease_id: last_refresh_time}
        self.leases: defaultdict[str, dict[str, float]] = defaultdict(dict)
        self.events: defaultdict[str, asyncio.Event] = defaultdict(asyncio.Event)
        scheduler.handlers.update(
            {
                "semaphore_register": self.create,
                "semaphore_acquire": self.acquire,
                "semaphore_release": self.release,
                "semaphore_refresh_leases": self.refresh_leases,
                "semaphore_value": self.get_value,
                "semaphore_close": self.close_sem,
            }
        )
        from distributed_tpu.rpc.core import PeriodicCallback

        scheduler.periodic_callbacks["semaphore-lease-check"] = PeriodicCallback(
            self._check_lease_timeouts, self.LEASE_TIMEOUT / 3
        )

    async def create(self, name: str = "", max_leases: int = 1) -> None:
        if name not in self.max_leases:
            self.max_leases[name] = max_leases
        elif self.max_leases[name] != max_leases:
            raise ValueError(
                f"semaphore {name!r} exists with max_leases="
                f"{self.max_leases[name]}"
            )

    async def acquire(self, name: str = "", timeout: float | None = None,
                      lease_id: str = "") -> bool:
        deadline = None if timeout is None else time() + timeout
        while len(self.leases[name]) >= self.max_leases.get(name, 1):
            remaining = None if deadline is None else deadline - time()
            if remaining is not None and remaining <= 0:
                return False
            event = self.events[name]
            try:
                await asyncio.wait_for(event.wait(), remaining)
            except asyncio.TimeoutError:
                return False
        self.leases[name][lease_id or uuid.uuid4().hex] = time()
        return True

    async def release(self, name: str = "", lease_id: str = "") -> bool:
        if lease_id in self.leases.get(name, {}):
            del self.leases[name][lease_id]
            self._wake(name)
            return True
        return False

    async def refresh_leases(self, name: str = "",
                             lease_ids: list[str] = ()) -> None:
        now = time()
        for lid in lease_ids:
            if lid in self.leases.get(name, {}):
                self.leases[name][lid] = now

    async def get_value(self, name: str = "") -> int:
        return len(self.leases.get(name, {}))

    async def close_sem(self, name: str = "") -> None:
        self.max_leases.pop(name, None)
        self.leases.pop(name, None)
        self._wake(name)
        self.events.pop(name, None)

    def _wake(self, name: str) -> None:
        ev = self.events.get(name)
        if ev is not None:
            ev.set()
            self.events[name] = asyncio.Event()

    async def _check_lease_timeouts(self) -> None:
        """Expire leases whose holder stopped refreshing (crashed client)."""
        now = time()
        for name, leases in list(self.leases.items()):
            expired = [
                lid for lid, t in leases.items()
                if now - t > self.LEASE_TIMEOUT
            ]
            for lid in expired:
                logger.info("semaphore %r lease %s expired", name, lid[:8])
                del leases[lid]
            if expired:
                self._wake(name)


class QueueExtension:
    """Named FIFO queues holding data or future keys (reference queues.py:17)."""

    def __init__(self, scheduler: "Scheduler"):
        self.scheduler = scheduler
        self.queues: dict[str, asyncio.Queue] = {}
        self.client_refcount: dict[str, int] = {}
        self.client_name = "queue-extension"
        scheduler.handlers.update(
            {
                "queue_create": self.create,
                "queue_put": self.put,
                "queue_get": self.get,
                "queue_qsize": self.qsize,
                "queue_release": self.release,
            }
        )

    async def create(self, name: str = "", maxsize: int = 0) -> None:
        if name not in self.queues:
            self.queues[name] = asyncio.Queue(maxsize=maxsize)
            self.client_refcount[name] = 1
        else:
            self.client_refcount[name] += 1

    async def put(self, name: str = "", value: Any = None, key: str | None = None,
                  timeout: float | None = None) -> None:
        if key is not None:
            record = {"type": "Future", "value": key}
        else:
            record = {"type": "msgpack", "value": value}
        await asyncio.wait_for(self.queues[name].put(record), timeout)
        if key is not None:
            # hold the future alive under this extension's client — only
            # after the put succeeded, or a timeout would leak the key
            self.scheduler.state.client_desires_keys([key], self.client_name)

    async def get(self, name: str = "", timeout: float | None = None,
                  batch: bool = False) -> Any:
        q = self.queues[name]
        if batch:
            out = []
            while not q.empty():
                out.append(q.get_nowait())
            return out
        return await asyncio.wait_for(q.get(), timeout)

    async def qsize(self, name: str = "") -> int:
        return self.queues[name].qsize()

    async def release(self, name: str = "") -> None:
        if name not in self.queues:
            return
        self.client_refcount[name] -= 1
        if self.client_refcount[name] <= 0:
            del self.client_refcount[name]
            q = self.queues.pop(name)
            keys = [
                r["value"] for r in q._queue  # type: ignore[attr-defined]
                if r["type"] == "Future"
            ]
            if keys:
                cm, wm = self.scheduler.state.client_releases_keys(
                    keys, self.client_name, seq_name("queue-release")
                )
                self.scheduler.send_all(cm, wm)


class VariableExtension:
    """Named mutable cells (reference variable.py:21)."""

    def __init__(self, scheduler: "Scheduler"):
        self.scheduler = scheduler
        self.variables: dict[str, dict] = {}
        self.waiting_conditions: defaultdict[str, asyncio.Condition] = defaultdict(
            asyncio.Condition
        )
        self.started = asyncio.Condition()
        self.client_name = "variable-extension"
        scheduler.handlers.update(
            {
                "variable_set": self.set,
                "variable_get": self.get,
                "variable_delete": self.delete,
            }
        )

    async def set(self, name: str = "", value: Any = None,
                  key: str | None = None) -> None:
        if key is not None:
            record = {"type": "Future", "value": key}
            self.scheduler.state.client_desires_keys([key], self.client_name)
        else:
            record = {"type": "msgpack", "value": value}
        old = self.variables.get(name)
        self.variables[name] = record
        if old is not None and old["type"] == "Future" and old["value"] != key:
            cm, wm = self.scheduler.state.client_releases_keys(
                [old["value"]], self.client_name, seq_name("variable-set")
            )
            self.scheduler.send_all(cm, wm)
        async with self.waiting_conditions[name]:
            self.waiting_conditions[name].notify_all()

    async def get(self, name: str = "", timeout: float | None = None) -> dict:
        if name not in self.variables:
            async def _wait():
                async with self.waiting_conditions[name]:
                    await self.waiting_conditions[name].wait_for(
                        lambda: name in self.variables
                    )

            await asyncio.wait_for(_wait(), timeout)
        return self.variables[name]

    async def delete(self, name: str = "") -> None:
        record = self.variables.pop(name, None)
        if record is not None and record["type"] == "Future":
            cm, wm = self.scheduler.state.client_releases_keys(
                [record["value"]], self.client_name, seq_name("variable-del")
            )
            self.scheduler.send_all(cm, wm)
        self.waiting_conditions.pop(name, None)


class PublishExtension:
    """Named published datasets (reference publish.py:10)."""

    def __init__(self, scheduler: "Scheduler"):
        self.scheduler = scheduler
        self.datasets: dict[str, dict] = {}
        self.client_name = "published-datasets"
        scheduler.handlers.update(
            {
                "publish_put": self.put,
                "publish_get": self.get,
                "publish_delete": self.delete,
                "publish_list": self.list,
            }
        )

    async def put(self, name: str = "", keys: list = (), data: Any = None,
                  override: bool = False, client: str | None = None) -> None:
        if name in self.datasets and not override:
            raise KeyError(f"dataset {name!r} already exists")
        self.scheduler.state.client_desires_keys(keys, self.client_name)
        self.datasets[name] = {"data": data, "keys": list(keys)}

    async def get(self, name: str = "") -> dict | None:
        return self.datasets.get(name)

    async def delete(self, name: str = "") -> None:
        out = self.datasets.pop(name, None)
        if out is not None and out["keys"]:
            cm, wm = self.scheduler.state.client_releases_keys(
                out["keys"], self.client_name, seq_name("unpublish")
            )
            self.scheduler.send_all(cm, wm)

    async def list(self) -> list[str]:
        return list(self.datasets)


class PubSubSchedulerExtension:
    """Topic pub/sub relay (reference pubsub.py:19).

    All delivery relays through the scheduler: publishers send
    ``pubsub-msg`` on their batched stream, the extension fans it out to
    every subscribed worker and client except the sender.  (The reference
    additionally short-circuits worker->worker delivery peer-to-peer,
    pubsub.py:120; that optimization can sit on top of this relay without
    protocol changes.)
    """

    def __init__(self, scheduler: "Scheduler"):
        self.scheduler = scheduler
        self.subscribers: defaultdict[str, set[str]] = defaultdict(set)
        self.client_subscribers: defaultdict[str, set[str]] = defaultdict(set)
        scheduler.stream_handlers.update(
            {
                "pubsub-add-subscriber": self.add_subscriber,
                "pubsub-remove-subscriber": self.remove_subscriber,
                "pubsub-msg": self.handle_message,
            }
        )

    def add_subscriber(self, name: str = "", worker: str = "",
                       client: str = "", **kw: Any) -> None:
        if worker:
            self.subscribers[name].add(worker)
        elif client:
            self.client_subscribers[name].add(client)

    def remove_subscriber(self, name: str = "", worker: str = "",
                          client: str = "", **kw: Any) -> None:
        if worker:
            self.subscribers[name].discard(worker)
        elif client:
            self.client_subscribers[name].discard(client)

    def handle_message(self, name: str = "", msg: Any = None,
                       worker: str = "", client: str = "", **kw: Any) -> None:
        # relay to subscribed clients (except the sender)
        for c in list(self.client_subscribers[name]):
            if c != client:
                self.scheduler.report(
                    {"op": "pubsub-msg", "name": name, "msg": msg}, client=c
                )
        # relay to subscribed workers (except the sender)
        for addr in self.subscribers[name]:
            if addr != worker:
                self.scheduler.send_all({}, {addr: [{
                    "op": "pubsub-msg", "name": name, "msg": msg,
                }]})


def coordination_extensions() -> dict[str, Any]:
    return {
        "events": EventExtension,
        "locks": LockExtension,
        "multi_locks": MultiLockExtension,
        "semaphores": SemaphoreExtension,
        "queues": QueueExtension,
        "variables": VariableExtension,
        "publish": PublishExtension,
        "pubsub": PubSubSchedulerExtension,
    }
