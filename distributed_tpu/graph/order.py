"""Static task prioritization — the ``dask.order.order`` equivalent.

The reference offloads ``dask.order.order`` at graph intake
(scheduler.py:4713) to produce a per-task static rank that becomes the third
element of the scheduler priority tuple (scheduler.py:4934).  The rank's job
is *memory-footprint minimization*: run graphs depth-first so intermediate
results are consumed (and released) soon after they are produced, rather than
breadth-first which materializes whole layers.

This implementation is a depth-first postorder from terminal tasks with two
of dask.order's load-bearing heuristics:

1. process terminal tasks grouped by connected component, smallest critical
   path first, so independent subgraphs do not interleave;
2. among a task's dependencies, visit the one whose subtree is "most
   exclusive" (fewest external dependents, then smaller reach) first, so
   shared inputs are computed late enough to be consumed promptly by all
   waiters but early enough not to stall.

Pure python, O(V + E log E); offloaded to a thread at graph intake like the
reference.  Deterministic: ties broken by key.
"""

from __future__ import annotations

from collections.abc import Mapping

Key = str


def order(dependencies: Mapping[Key, set[Key]]) -> dict[Key, int]:
    """Return ``{key: rank}`` with lower rank = higher scheduling priority.

    ``dependencies`` maps every key to the set of keys it depends on; every
    dependency must itself appear as a key.
    """
    if not dependencies:
        return {}

    dependents: dict[Key, list[Key]] = {k: [] for k in dependencies}
    for k, deps in dependencies.items():
        for d in deps:
            dependents[d].append(k)

    num_dependents = {k: len(v) for k, v in dependents.items()}

    # height: length of the longest chain of dependencies below each node
    # (iterative topological pass from leaves up)
    height: dict[Key, int] = {}
    indeg = {k: len(deps) for k, deps in dependencies.items()}
    stack = [k for k, d in indeg.items() if d == 0]
    remaining = dict(indeg)
    while stack:
        node = stack.pop()
        deps = dependencies[node]
        height[node] = 1 + max((height[d] for d in deps), default=-1)
        for parent in dependents[node]:
            remaining[parent] -= 1
            if remaining[parent] == 0:
                stack.append(parent)
    if len(height) != len(dependencies):
        raise ValueError("cycle detected in graph")

    # terminal tasks (no dependents), ordered: shallow components first so
    # quick outputs finish before deep pipelines begin
    terminals = sorted(
        (k for k, n in num_dependents.items() if n == 0),
        key=lambda k: (height[k], k),
    )

    result: dict[Key, int] = {}
    counter = 0

    def dep_sort_key(d: Key):
        # most-exclusive dependency first: few dependents, short reach
        return (num_dependents[d], height[d], d)

    for term in terminals:
        if term in result:
            continue
        # iterative DFS, postorder numbering
        dfs_stack: list[tuple[Key, bool]] = [(term, False)]
        while dfs_stack:
            node, processed = dfs_stack.pop()
            if node in result:
                continue
            if processed:
                result[node] = counter
                counter += 1
                continue
            dfs_stack.append((node, True))
            deps = [d for d in dependencies[node] if d not in result]
            # push in reverse so the best-ranked dep is visited first
            for d in sorted(deps, key=dep_sort_key, reverse=True):
                dfs_stack.append((d, False))
    return result


def validate_order(dependencies: Mapping[Key, set[Key]], ranks: Mapping[Key, int]) -> None:
    """Oracle check: every task ranks after all of its dependencies."""
    for k, deps in dependencies.items():
        for d in deps:
            assert ranks[d] < ranks[k], (d, k, ranks[d], ranks[k])
    assert sorted(ranks.values()) == list(range(len(dependencies)))
