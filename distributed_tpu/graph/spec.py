"""Task-graph specification.

The reference has no graph spec of its own — it consumes dask's
``HighLevelGraph`` (materialized at scheduler.py:8874) where a task is a
nested tuple ``(func, arg0, arg1, ...)`` and dependencies are discovered by
scanning args for keys.  We define a cleaner explicit spec: a ``TaskSpec``
holds the callable plus args/kwargs in which dependencies appear as
``TaskRef(key)`` markers, so dependency discovery is unambiguous (no string
collision hazards) and substitution at execution time is a mechanical walk.

A ``Graph`` is ``{key: TaskSpec | literal}``; literals are inline data.
"""

from __future__ import annotations

import uuid
from collections.abc import Callable, Hashable, Iterator, Mapping
from typing import Any

Key = str


class TaskRef:
    """Marker for a dependency on another task's output."""

    __slots__ = ("key",)

    def __init__(self, key: Key):
        self.key = key

    def __repr__(self) -> str:
        return f"TaskRef({self.key!r})"

    def __eq__(self, other: object) -> bool:
        return isinstance(other, TaskRef) and other.key == self.key

    def __hash__(self) -> int:
        return hash(("TaskRef", self.key))


class TaskSpec:
    """One task: ``fn(*args, **kwargs)`` with TaskRef placeholders.

    Equivalent to the reference's ``TaskState.run_spec``
    (scheduler.py:1188-1196) — an opaque callable plus arguments; the
    scheduler never introspects beyond dependencies.
    """

    __slots__ = ("fn", "args", "kwargs")

    def __init__(self, fn: Callable, args: tuple = (), kwargs: dict | None = None):
        self.fn = fn
        self.args = args
        self.kwargs = kwargs or {}

    def dependencies(self) -> set[Key]:
        deps: set[Key] = set()
        _scan_refs(self.args, deps)
        _scan_refs(self.kwargs, deps)
        return deps

    def substitute(self, data: Mapping[Key, Any]) -> tuple[Callable, tuple, dict]:
        """Replace TaskRefs with concrete values for execution."""
        args = _sub(self.args, data)
        kwargs = _sub(self.kwargs, data)
        return self.fn, args, kwargs

    def __repr__(self) -> str:
        from distributed_tpu.utils import funcname

        return f"TaskSpec({funcname(self.fn)}, {len(self.args)} args)"


def _scan_refs(obj: Any, out: set[Key]) -> None:
    if isinstance(obj, TaskRef):
        out.add(obj.key)
    elif isinstance(obj, (list, tuple, set, frozenset)):
        for o in obj:
            _scan_refs(o, out)
    elif isinstance(obj, dict):
        for v in obj.values():
            _scan_refs(v, out)


def _sub(obj: Any, data: Mapping[Key, Any]) -> Any:
    if isinstance(obj, TaskRef):
        return data[obj.key]
    if isinstance(obj, tuple):
        return tuple(_sub(o, data) for o in obj)
    if isinstance(obj, list):
        return [_sub(o, data) for o in obj]
    if isinstance(obj, dict):
        return {k: _sub(v, data) for k, v in obj.items()}
    return obj


class Graph:
    """A task graph: ``{key: TaskSpec | literal-data}``."""

    def __init__(self, tasks: Mapping[Key, Any] | None = None):
        self.tasks: dict[Key, Any] = dict(tasks or {})

    def __len__(self) -> int:
        return len(self.tasks)

    def __iter__(self) -> Iterator[Key]:
        return iter(self.tasks)

    def __getitem__(self, key: Key) -> Any:
        return self.tasks[key]

    def __setitem__(self, key: Key, value: Any) -> None:
        self.tasks[key] = value

    def __contains__(self, key: object) -> bool:
        return key in self.tasks

    def add(self, fn: Callable, *args: Any, key: Key | None = None, **kwargs: Any) -> Key:
        from distributed_tpu.utils import funcname

        if key is None:
            key = f"{funcname(fn)}-{uuid.uuid4().hex[:16]}"
        self.tasks[key] = TaskSpec(fn, args, kwargs)
        return key

    def dependencies(self) -> dict[Key, set[Key]]:
        out: dict[Key, set[Key]] = {}
        for key, spec in self.tasks.items():
            out[key] = spec.dependencies() if isinstance(spec, TaskSpec) else set()
        return out

    def validate(self) -> None:
        deps = self.dependencies()
        for key, ds in deps.items():
            for d in ds:
                if d not in self.tasks:
                    raise ValueError(f"task {key!r} depends on missing key {d!r}")
        # cycle check via iterative DFS
        WHITE, GRAY, BLACK = 0, 1, 2
        color = dict.fromkeys(self.tasks, WHITE)
        for root in self.tasks:
            if color[root] != WHITE:
                continue
            stack: list[tuple[Key, Iterator[Key]]] = [(root, iter(deps[root]))]
            color[root] = GRAY
            while stack:
                node, it = stack[-1]
                advanced = False
                for child in it:
                    if color[child] == GRAY:
                        raise ValueError(f"cycle detected involving {child!r}")
                    if color[child] == WHITE:
                        color[child] = GRAY
                        stack.append((child, iter(deps[child])))
                        advanced = True
                        break
                if not advanced:
                    color[node] = BLACK
                    stack.pop()


def tokenize(*args: Hashable) -> str:
    """Deterministic-ish content token for key generation."""
    import hashlib
    import pickle

    try:
        payload = pickle.dumps(args, protocol=5)
    except Exception:
        payload = repr(args).encode()
    return hashlib.sha1(payload).hexdigest()[:16]
