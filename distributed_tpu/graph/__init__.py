from distributed_tpu.graph.order import order, validate_order
from distributed_tpu.graph.spec import Graph, Key, TaskRef, TaskSpec, tokenize

__all__ = ["Graph", "Key", "TaskRef", "TaskSpec", "order", "tokenize", "validate_order"]
